# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Pipeline parallelism: GPipe ppermute pipeline == sequential layer scan.

The reference has no pipeline parallelism (SURVEY §2.20); these tests hold
the TPU-native pipeline to exact numerical parity with the plain stacked
scan, and to training-trajectory parity with single-device execution when
composed with DP and ZeRO.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute composition suite (see pytest.ini)

from tiny_deepspeed_tpu import (
    AdamW, DDP, GPT2Model, GPTConfig, SingleDevice, Zero1, Zero2, Zero3,
    make_mesh,
)
from tiny_deepspeed_tpu.parallel.pipeline import spmd_pipeline


def tiny_cfg(**kw):
    kw.setdefault("block_size", 64)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("n_layer", 4)
    kw.setdefault("n_head", 2)
    kw.setdefault("n_embd", 32)
    kw.setdefault("compute_dtype", jnp.float32)
    return GPTConfig(**kw)


def batch(cfg, b=8, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    idx = jax.random.randint(k1, (b, cfg.block_size), 0, cfg.vocab_size,
                             jnp.int32)
    tgt = jax.random.randint(k2, (b, cfg.block_size), 0, cfg.vocab_size,
                             jnp.int32)
    return idx, tgt


def test_spmd_pipeline_matches_scan():
    """The pipeline primitive is numerically identical to lax.scan over
    the stacked layers."""
    mesh = make_mesh((2, 4), ("data", "pipe"))
    l, d, b = 8, 16, 4
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (l, d, d), jnp.float32) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (b, 6, d), jnp.float32)

    def block(c, wl):
        return c + jnp.tanh(c @ wl)

    def seq(w, x):
        def body(c, wl):
            return block(c, wl), None
        return jax.lax.scan(body, x, w)[0]

    got = jax.jit(
        lambda w, x: spmd_pipeline(block, w, x, mesh=mesh)
    )(w, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(seq(w, x)),
                               rtol=1e-5, atol=1e-5)


def test_spmd_pipeline_grads_match_scan():
    mesh = make_mesh((1, 8), ("data", "pipe"))
    l, d, b, m = 8, 16, 8, 4
    w = jax.random.normal(jax.random.PRNGKey(0), (l, d, d), jnp.float32) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (b, 4, d), jnp.float32)

    def block(c, wl):
        return c + jnp.tanh(c @ wl)

    def pipe_loss(w, x):
        return spmd_pipeline(
            block, w, x, mesh=mesh, microbatches=m
        ).sum()

    def seq_loss(w, x):
        def body(c, wl):
            return block(c, wl), None
        return jax.lax.scan(body, x, w)[0].sum()

    lp, gp = jax.jit(jax.value_and_grad(pipe_loss))(w, x)
    ls, gs = jax.value_and_grad(seq_loss)(w, x)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("engine_cls,stage",
                         [(DDP, 0), (Zero2, 2), (Zero3, 3)])
def test_pipeline_training_parity(engine_cls, stage):
    """dp=2 x pipe=4 training == single-device training, per step."""
    cfg = tiny_cfg()
    model = GPT2Model(cfg)
    idx, tgt = batch(cfg)

    ref_engine = SingleDevice(model, AdamW(lr=1e-3))
    ref_state = ref_engine.init(jax.random.PRNGKey(0))

    eng = engine_cls(model, AdamW(lr=1e-3), pipeline_parallel=4)
    state = eng.init(jax.random.PRNGKey(0))
    assert eng.pipe_axis == "pipe"
    assert eng.mesh.shape["pipe"] == 4 and eng.mesh.shape["data"] == 2

    for i in range(5):
        ref_state, ref_loss = ref_engine.step(ref_state, (idx, tgt))
        state, loss = eng.step(state, (idx, tgt))
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-4)

    # params: loose atol — AdamW's ~sign(g) first steps turn reduction-order
    # noise on near-zero grads into O(lr) param deltas (loss trajectory above
    # is the tight check, same tolerance as tests/test_engine.py)
    for name in state.params:
        np.testing.assert_allclose(
            np.asarray(state.params[name]),
            np.asarray(ref_state.params[name]),
            rtol=2e-3, atol=6e-3,
        )


def test_pipeline_with_zero1_and_microbatches():
    """pipe=2 x dp=4, M=4 microbatches, ZeRO-1: loss tracks single-device."""
    cfg = tiny_cfg()
    model = GPT2Model(cfg)
    idx, tgt = batch(cfg)

    ref_engine = SingleDevice(model, AdamW(lr=1e-3))
    ref_state = ref_engine.init(jax.random.PRNGKey(0))
    eng = Zero1(model, AdamW(lr=1e-3), pipeline_parallel=2,
                pipeline_microbatches=4)
    state = eng.init(jax.random.PRNGKey(0))

    ref_state, ref_loss = ref_engine.step(ref_state, (idx, tgt))
    state, loss = eng.step(state, (idx, tgt))
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_param_layout():
    """Stacked block params shard their layer axis over "pipe"; stage-3
    composes a data-axis shard on another dim."""
    cfg = tiny_cfg()
    model = GPT2Model(cfg)
    eng = Zero3(model, AdamW(lr=1e-3), pipeline_parallel=4)
    state = eng.init(jax.random.PRNGKey(0))
    spec = state.params["h.mlp.fc.w"].sharding.spec
    assert spec[0] == "pipe"
    assert "data" in spec


def test_pipeline_rejects_bad_shapes():
    cfg = tiny_cfg(n_layer=3)
    model = GPT2Model(cfg)
    with pytest.raises(ValueError, match="n_layer"):
        DDP(model, AdamW(lr=1e-3), pipeline_parallel=4)


def test_pipeline_rejects_incapable_model():
    """Models whose apply() has no pipeline path must be rejected, not
    silently run un-pipelined with the layer axis sharded."""
    class NoPipe(GPT2Model):
        pipeline_capable = False

    with pytest.raises(ValueError, match="pipeline_capable"):
        DDP(NoPipe(tiny_cfg()), AdamW(lr=1e-3), pipeline_parallel=2)


def test_microbatch_sweep_matches_scan():
    """Bubble amortization knob: every microbatch count M gives the same
    numerics; utilization M/(M+S-1) varies, results must not (round-1
    verdict #8's sweep)."""
    mesh = make_mesh((2, 4), ("data", "pipe"))
    l, d, b = 4, 16, 8
    w = jax.random.normal(jax.random.PRNGKey(0), (l, d, d), jnp.float32) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (b, 4, d), jnp.float32)

    def block(c, wl):
        return c + jnp.tanh(c @ wl)

    def seq(w, x):
        return jax.lax.scan(lambda c, wl: (block(c, wl), None), x, w)[0]

    ref = np.asarray(seq(w, x))
    for m in (4, 8, 2, 1):
        if b % m:
            continue
        got = jax.jit(lambda w, x, m=m: spmd_pipeline(
            block, w, x, mesh=mesh, microbatches=m
        ))(w, x)
        np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5,
                                   atol=1e-5, err_msg=f"microbatches={m}")


def test_pipeline_composes_with_seq_parallel():
    """pipeline v2: dp=2 x seq=2 x pipe=2 — ring attention runs inside the
    pipeline's manual region; loss matches single-device."""
    cfg = tiny_cfg()
    model = GPT2Model(cfg)
    idx, tgt = batch(cfg)

    ref_engine = SingleDevice(model, AdamW(lr=1e-3))
    ref_state = ref_engine.init(jax.random.PRNGKey(0))
    eng = Zero2(model, AdamW(lr=1e-3), seq_parallel=2, pipeline_parallel=2)
    assert eng.mesh.shape == {"data": 2, "seq": 2, "pipe": 2}
    state = eng.init(jax.random.PRNGKey(0))

    for _ in range(3):
        ref_state, ref_loss = ref_engine.step(ref_state, (idx, tgt))
        state, loss = eng.step(state, (idx, tgt))
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-4)


def test_moe_pipeline_capable():
    """pipeline v2: MoE runs under pipe=2 (aux loss threaded through the
    pipeline, bubble ticks masked) and tracks the un-pipelined loss."""
    from tiny_deepspeed_tpu import MoEConfig, MoEGPT
    cfg = MoEConfig(block_size=64, vocab_size=128, n_layer=2, n_head=2,
                    n_embd=32, n_expert=2, capacity_factor=2.0,
                    compute_dtype=jnp.float32)
    moe = MoEGPT(cfg)
    idx, tgt = batch(cfg)

    ref_engine = SingleDevice(moe, AdamW(lr=1e-3))
    ref_state = ref_engine.init(jax.random.PRNGKey(0))
    eng = Zero1(moe, AdamW(lr=1e-3), pipeline_parallel=2,
                tensor_parallel=2)
    state = eng.init(jax.random.PRNGKey(0))

    ref_state, ref_loss = ref_engine.step(ref_state, (idx, tgt))
    state, loss = eng.step(state, (idx, tgt))
    # aux is computed per microbatch (capacity truncation differs from the
    # full-batch route) — identical LM loss + small aux-term wiggle
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=5e-3, atol=5e-3)


def test_moe_pipeline_with_seq_parallel():
    """MoE under dp=2 x seq=2 x pipe=2: aux is pmean'd over seq shards (each
    routes its own token slice) so the replicated out_spec is honest; loss
    tracks single-device within routing tolerance."""
    from tiny_deepspeed_tpu import MoEConfig, MoEGPT
    cfg = MoEConfig(block_size=64, vocab_size=128, n_layer=2, n_head=2,
                    n_embd=32, n_expert=2, capacity_factor=2.0,
                    compute_dtype=jnp.float32)
    moe = MoEGPT(cfg)
    idx, tgt = batch(cfg)

    ref_engine = SingleDevice(moe, AdamW(lr=1e-3))
    ref_state = ref_engine.init(jax.random.PRNGKey(0))
    eng = Zero1(moe, AdamW(lr=1e-3), seq_parallel=2, pipeline_parallel=2)
    state = eng.init(jax.random.PRNGKey(0))

    ref_state, ref_loss = ref_engine.step(ref_state, (idx, tgt))
    state, loss = eng.step(state, (idx, tgt))
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=2e-2, atol=2e-2)


# -- 1F1B schedule ---------------------------------------------------------


class Test1F1B:
    def test_primitive_matches_autodiff(self):
        """spmd_pipeline_1f1b's explicit per-tick vjp grads == autodiff of
        the same scan+head composition."""
        from tiny_deepspeed_tpu.parallel.pipeline import spmd_pipeline_1f1b
        mesh = make_mesh((2, 4), ("data", "pipe"))
        l, d, b, t, m = 8, 16, 8, 6, 8
        w = jax.random.normal(jax.random.PRNGKey(0), (l, d, d),
                              jnp.float32) * 0.1
        hw = jax.random.normal(jax.random.PRNGKey(1), (d, d),
                               jnp.float32) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(2), (b, t, d), jnp.float32)
        tgt = jax.random.normal(jax.random.PRNGKey(3), (b, t, d),
                                jnp.float32)

        def block(c, wl):
            return c + jnp.tanh(c @ wl)

        def head(hp, y, tg):
            return jnp.mean(jnp.square(y @ hp["w"] - tg))

        def ref(w, hp, x):
            def body(c, wl):
                return block(c, wl), None
            y = jax.lax.scan(body, x, w)[0]
            # mean over equal-size microbatches == full-batch mean
            return head(hp, y, tgt)

        ref_loss, (dw_r, dh_r, dx_r) = jax.value_and_grad(
            ref, argnums=(0, 1, 2)
        )(w, {"w": hw}, x)

        loss, dw, dh, dx = jax.jit(
            lambda w, hp, x, tg: spmd_pipeline_1f1b(
                block, head, w, hp, x, tg, mesh=mesh, microbatches=m
            )
        )(w, {"w": hw}, x, tgt)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_r),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dh["w"]),
                                   np.asarray(dh_r["w"]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_r),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("engine_cls", [DDP, Zero3])
    def test_training_parity(self, engine_cls):
        """1F1B training == single-device training, dp=2 x pipe=4, M=2S."""
        cfg = tiny_cfg()
        model = GPT2Model(cfg)
        idx, tgt = batch(cfg)

        ref_engine = SingleDevice(model, AdamW(lr=1e-3))
        ref_state = ref_engine.init(jax.random.PRNGKey(0))
        eng = engine_cls(model, AdamW(lr=1e-3), pipeline_parallel=4,
                         pipeline_microbatches=8,
                         pipeline_schedule="1f1b")
        state = eng.init(jax.random.PRNGKey(0))

        for _ in range(3):
            ref_state, ref_loss = ref_engine.step(ref_state, (idx, tgt))
            state, loss = eng.step(state, (idx, tgt))
            np.testing.assert_allclose(float(loss), float(ref_loss),
                                       rtol=2e-4, atol=2e-4)

    def test_memory_bounded_at_stages_not_microbatches(self):
        """The property 1F1B buys: at M = 4S the compiled step's temp bytes
        undercut GPipe's, whose in-flight activations grow with M."""
        cfg = tiny_cfg(n_layer=4, remat=False)
        model = GPT2Model(cfg)
        idx, tgt = batch(cfg, b=16)

        def temp_bytes(schedule):
            eng = Zero1(model, AdamW(lr=1e-3), pipeline_parallel=4,
                        pipeline_microbatches=16,
                        pipeline_schedule=schedule)
            state = eng.init(jax.random.PRNGKey(0))
            c = eng._step.lower(state, (idx, tgt)).compile()
            return c.memory_analysis().temp_size_in_bytes

        b_1f1b, b_gpipe = temp_bytes("1f1b"), temp_bytes("gpipe")
        assert b_1f1b < b_gpipe, (b_1f1b, b_gpipe)

    def test_llama_supports_1f1b(self):
        from tiny_deepspeed_tpu import LlamaConfig, LlamaModel
        cfg = LlamaConfig(block_size=64, vocab_size=128, n_layer=4,
                          n_head=4, n_kv_head=2, n_embd=32,
                          compute_dtype=jnp.float32)
        model = LlamaModel(cfg)
        idx, tgt = batch(cfg)
        ref = SingleDevice(model, AdamW(lr=1e-3))
        ref_state = ref.init(jax.random.PRNGKey(0))
        eng = Zero2(model, AdamW(lr=1e-3), pipeline_parallel=2,
                    pipeline_microbatches=4, pipeline_schedule="1f1b")
        state = eng.init(jax.random.PRNGKey(0))
        ref_state, ref_loss = ref.step(ref_state, (idx, tgt))
        state, loss = eng.step(state, (idx, tgt))
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-4)

    def test_moe_1f1b_matches_gpipe(self):
        """MoE aux loss through the 1F1B schedule: with identical
        microbatching the routing (and so the loss) matches GPipe
        tightly — the aux seeds the backward as a constant cotangent."""
        from tiny_deepspeed_tpu import MoEConfig, MoEGPT
        # aux_loss_weight raised well above the 1e-2 default and 6 steps:
        # at the defaults a wrong aux-cotangent SCALE (e.g. an extra /m)
        # stays under a 2e-4 tolerance — this config trips it
        cfg = MoEConfig(block_size=64, vocab_size=128, n_layer=2,
                        n_head=2, n_embd=32, n_expert=2,
                        capacity_factor=2.0, aux_loss_weight=0.5,
                        compute_dtype=jnp.float32)
        moe = MoEGPT(cfg)
        idx, tgt = batch(cfg)

        def run(schedule, sp=1):
            eng = Zero1(moe, AdamW(lr=1e-3), pipeline_parallel=2,
                        pipeline_microbatches=4, seq_parallel=sp,
                        pipeline_schedule=schedule)
            state = eng.init(jax.random.PRNGKey(0))
            losses = []
            for _ in range(6):
                state, loss = eng.step(state, (idx, tgt))
                losses.append(float(loss))
            return losses

        np.testing.assert_allclose(run("1f1b"), run("gpipe"),
                                   rtol=2e-4, atol=2e-4)
        # aux under seq parallel: the 1/n_sp aux-cotangent seeding — at
        # aux_loss_weight=0.5 over 6 steps a wrong scale trips 2e-4
        np.testing.assert_allclose(run("1f1b", sp=2), run("gpipe", sp=2),
                                   rtol=2e-4, atol=2e-4)

        # and the full composition: MoE aux + dropout + 1F1B in one step
        # (tuple-xs slab scan with with_aux AND merged dropout_rng)
        import dataclasses
        dcfg = dataclasses.replace(cfg, dropout=0.2)
        dmoe = MoEGPT(dcfg)
        eng = Zero1(dmoe, AdamW(lr=1e-3), pipeline_parallel=2,
                    pipeline_microbatches=4, pipeline_schedule="1f1b")
        state = eng.init(jax.random.PRNGKey(0))
        state, loss = eng.step(state, batch(dcfg))
        assert 0 < float(loss) < 20

    def test_rejections(self):
        class NoSched(GPT2Model):
            supports_1f1b = False

        with pytest.raises(ValueError, match="1F1B"):
            Zero1(NoSched(tiny_cfg()), AdamW(lr=1e-3),
                  pipeline_parallel=2, pipeline_schedule="1f1b")
        with pytest.raises(ValueError, match="pipeline_schedule"):
            Zero1(GPT2Model(tiny_cfg()), AdamW(lr=1e-3),
                  pipeline_parallel=2, pipeline_schedule="interleaved")
    def test_fp8_gather_matches_gpipe(self):
        """gather_quant="fp8" under 1F1B: the f8 stacked cotangents
        accumulate f32 across ticks and cross the e4m3 edge once at the
        boundary — trajectory matches the GPipe fp8 path tightly."""
        cfg = tiny_cfg(gather_quant="fp8")
        model = GPT2Model(cfg)
        b = batch(cfg)

        def run(schedule):
            eng = Zero3(model, AdamW(lr=1e-3), pipeline_parallel=2,
                        pipeline_microbatches=4,
                        pipeline_schedule=schedule)
            state = eng.init(jax.random.PRNGKey(0))
            losses = []
            for _ in range(5):
                state, loss = eng.step(state, b)
                losses.append(float(loss))
            return losses

        np.testing.assert_allclose(run("1f1b"), run("gpipe"),
                                   rtol=1e-5, atol=1e-5)

    def test_accum_steps_compose(self):
        """1F1B inside the engine's microbatch-accumulation scan: a
        (2, 8, T) accumulated step matches the (16, T) one-shot step."""
        cfg = tiny_cfg()
        model = GPT2Model(cfg)
        idx, tgt = batch(cfg, b=16)
        kw = dict(pipeline_parallel=2, pipeline_microbatches=4,
                  pipeline_schedule="1f1b")
        e1 = Zero1(model, AdamW(lr=1e-3), **kw)
        e2 = Zero1(model, AdamW(lr=1e-3), accum_steps=2, **kw)
        s1 = e1.init(jax.random.PRNGKey(0))
        s2 = e2.init(jax.random.PRNGKey(0))
        s1, l1 = e1.step(s1, (idx, tgt))
        s2, l2 = e2.step(
            s2, (idx.reshape(2, 8, -1), tgt.reshape(2, 8, -1))
        )
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

    def test_loss_scaling_compose(self):
        """Static AMP loss scale seeds the 1F1B backward (loss_seed); the
        unscaled result matches the unscaled run step for step."""
        cfg = tiny_cfg()
        model = GPT2Model(cfg)
        b = batch(cfg)
        kw = dict(pipeline_parallel=2, pipeline_microbatches=4,
                  pipeline_schedule="1f1b")
        e1 = Zero1(model, AdamW(lr=1e-3), **kw)
        e2 = Zero1(model, AdamW(lr=1e-3), loss_scale=2.0 ** 12, **kw)
        s1 = e1.init(jax.random.PRNGKey(0))
        s2 = e2.init(jax.random.PRNGKey(0))
        for _ in range(3):
            s1, l1 = e1.step(s1, b)
            s2, l2 = e2.step(s2, b)
            np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)

    def test_dropout_trains_and_is_deterministic(self):
        """1F1B + dropout: keys ride outside the differentiated args,
        folded per microbatch.  Same state + same step => identical loss
        (masks reproduce); training decreases loss; eval (no rng) differs
        from train loss (masks were really on)."""
        cfg = tiny_cfg(dropout=0.2)
        model = GPT2Model(cfg)
        b = batch(cfg)
        eng = Zero1(model, AdamW(lr=1e-3), pipeline_parallel=2,
                    pipeline_microbatches=4, pipeline_schedule="1f1b")
        state = eng.init(jax.random.PRNGKey(0))
        ev = float(eng.eval_loss(state, b))  # before step: state donates
        _, l_a = eng.step(state, b)
        state = eng.init(jax.random.PRNGKey(0))
        _, l_b = eng.step(state, b)
        assert float(l_a) == float(l_b)  # deterministic replay
        assert abs(float(l_a) - ev) > 1e-4  # train DID use masks
        state = eng.init(jax.random.PRNGKey(0))
        losses = []
        for _ in range(8):
            state, loss = eng.step(state, b)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


@pytest.mark.parametrize("seq_impl", ["ring", "ulysses"])
def test_1f1b_composes_with_seq_parallel(seq_impl):
    """1F1B manual over {pipe, seq}: ring/Ulysses attention runs inside
    the slab, the head sees local token slices (loss = seq-pmean of local
    means, vjps seeded 1/n), and the trajectory matches single-device."""
    cfg = tiny_cfg()
    model = GPT2Model(cfg)
    idx, tgt = batch(cfg)

    ref = SingleDevice(model, AdamW(lr=1e-3))
    ref_state = ref.init(jax.random.PRNGKey(0))
    eng = Zero2(model, AdamW(lr=1e-3), seq_parallel=2, pipeline_parallel=2,
                pipeline_microbatches=4, pipeline_schedule="1f1b",
                seq_impl=seq_impl)
    state = eng.init(jax.random.PRNGKey(0))
    for _ in range(3):
        ref_state, ref_loss = ref.step(ref_state, (idx, tgt))
        state, loss = eng.step(state, (idx, tgt))
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-4)
