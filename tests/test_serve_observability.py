# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Serving observability (ISSUE 9): request-lifecycle tracing, per-tick
time series, the serving flight recorder, tail-latency attribution, and
the ICI-vs-DCN ledger split.

Acceptance pins:
  * every terminal request's latency components PARTITION its terminal
    latency (sum(comp_*_s) == lat_s within rounding) — the attribution
    dashboard's numbers are exact, not estimates;
  * a chaos run's Perfetto export is STRICT-parseable JSON with one
    track per decode slot plus a queue track, the poisoned slot's
    quarantine and the watchdog restart visible as markers, and
    tick-segment span walls summing to within each tick's measured wall;
  * the `flight` record flushed on a watchdog restart covers the ticks
    LEADING UP to it (ring semantics, at_step = the restart tick);
  * `tick` records pass the schema gate (report_run.py --check) and the
    event-triggered + sampled emission bounds quiet-traffic volume;
  * `wire_link_split` pins cross-slice (DCN) bytes from the compiled
    replica_groups on a CPU-emulated 2-slice mesh: intra-slice
    collectives bill to ICI, slice-spanning ones to DCN.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import GPTConfig, GPT2Model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = dict(block_size=64, vocab_size=128, n_layer=2, n_head=2,
           n_embd=32, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return GPT2Model(GPTConfig(**CFG))


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0))


def _logger(path, serve_cfg=None):
    from tiny_deepspeed_tpu.telemetry.schema import SCHEMA_VERSION
    from tiny_deepspeed_tpu.utils.profiling import MetricsLogger
    lg = MetricsLogger(str(path), stdout=False)
    meta = dict(schema_version=SCHEMA_VERSION, engine="serve:test",
                model="tiny")
    if serve_cfg is not None:
        meta["serve"] = dict(max_active=serve_cfg.max_active,
                             num_blocks=serve_cfg.num_blocks,
                             block_tokens=serve_cfg.block_tokens)
    lg.log_meta(**meta)
    return lg


@pytest.fixture(scope="module")
def preempt_run(model, params, tmp_path_factory):
    """A tight-pool run that exercises queue wait, preemption, and
    natural completion — the clean-path attribution fixture.  One
    engine, reused by several tests (XLA compiles dominate)."""
    from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
    path = tmp_path_factory.mktemp("serveobs") / "preempt.jsonl"
    cfg = ServeConfig(max_active=3, num_blocks=8, block_tokens=8,
                      max_seq_tokens=40, tick_record_every=4)
    lg = _logger(path, cfg)
    eng = ServingEngine(model, params, cfg, logger=lg)
    reqs = [eng.submit([1 + i, 2, 3, 4 + i], 20) for i in range(4)]
    eng.drain()
    lg.close()
    return str(path), reqs, eng


@pytest.fixture(scope="module")
def chaos_run(model, params, tmp_path_factory):
    """A poisoned run: one quarantine, then a watchdog warm restart
    (guard_k_restart=1 — the first poisoned tick trips it), then clean
    completion.  Drives the flight-flush, restart-overhead, and
    trace-marker pins."""
    from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
    path = tmp_path_factory.mktemp("serveobs") / "chaos.jsonl"
    cfg = ServeConfig(max_active=2, num_blocks=16, block_tokens=8,
                      max_seq_tokens=32, guard_k_restart=1,
                      tick_record_every=1)
    lg = _logger(path, cfg)
    eng = ServingEngine(model, params, cfg, logger=lg)
    reqs = [eng.submit([1, 2, 3, 4], 12), eng.submit([5, 6, 7, 8], 12)]
    eng.tick()            # admit both
    eng.poison_slot(0)
    eng.tick()            # quarantine slot 0 AND trip the watchdog
    eng.drain()
    lg.close()
    return str(path), reqs, eng


def _records(path, kind=None):
    out = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


class TestLatencyAttribution:
    COMPONENTS = ("comp_queue_s", "comp_prefill_s", "comp_decode_s",
                  "comp_preempt_s", "comp_restart_s")

    def test_components_partition_latency(self, preempt_run):
        """The headline pin: per-request component sums equal terminal
        latency within measurement noise (here: 6-decimal rounding of
        the shared-timestamp partition — sub-millisecond)."""
        path, reqs, _ = preempt_run
        recs = _records(path, "request")
        assert len(recs) == 4
        for rec in recs:
            total = sum(rec[k] for k in self.COMPONENTS)
            assert total == pytest.approx(rec["lat_s"], abs=1e-3), rec

    def test_preempted_request_pays_preempt_wait(self, preempt_run):
        path, reqs, _ = preempt_run
        assert any(r.preemptions > 0 for r in reqs), \
            "fixture rotted: the tight pool no longer preempts"
        recs = {r["request_id"]: r for r in _records(path, "request")}
        for r in reqs:
            if r.preemptions:
                assert recs[r.id]["comp_preempt_s"] > 0.0

    def test_restart_overhead_attributed(self, chaos_run):
        """The surviving neighbor of the watchdog restart pays
        restart-overhead (restart re-queue -> re-admission), NOT
        preempted-wait — the dashboard must bill the watchdog."""
        path, reqs, eng = chaos_run
        assert eng.restarts == 1
        recs = {r["request_id"]: r for r in _records(path, "request")}
        survivor = [r for r in reqs if r.status == "ok"]
        assert survivor, "fixture rotted: nobody survived the restart"
        assert any(recs[r.id]["comp_restart_s"] > 0.0 for r in survivor)
        for rec in recs.values():
            total = sum(rec[k] for k in self.COMPONENTS)
            assert total == pytest.approx(rec["lat_s"], abs=1e-3), rec

    def test_lifecycle_events_on_record(self, chaos_run):
        path, reqs, _ = chaos_run
        recs = {r["request_id"]: r for r in _records(path, "request")}
        failed = [r for r in reqs if r.status == "failed"][0]
        names = [e[0] for e in recs[failed.id]["events"]]
        assert names[0] == "submitted"
        assert "admitted" in names and "quarantined" in names
        assert names[-1] == "terminal:failed"
        ok = [r for r in reqs if r.status == "ok"][0]
        names = [e[0] for e in recs[ok.id]["events"]]
        assert "restart_requeued" in names
        assert names[-1] == "terminal:ok"
        # events share one monotonic clock: non-decreasing stamps
        for rec in recs.values():
            ts = [e[1] for e in rec["events"]]
            assert ts == sorted(ts)


class TestPrefillFailureRequeue:
    def test_real_prefill_exception_requeues_and_terminates(
            self, model, params, tmp_path):
        """A REAL exception out of the compiled prefill (not the chaos
        hook, which re-queues by hand) must not strand the request in a
        non-terminal limbo: the admission path puts it back at the
        front, the watchdog warm-restarts, and the request still ends
        in exactly one terminal status with an exact component
        partition."""
        from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
        path = tmp_path / "prefill_fail.jsonl"
        cfg = ServeConfig(max_active=2, num_blocks=16, block_tokens=8,
                          max_seq_tokens=32, tick_record_every=1)
        lg = _logger(path, cfg)
        eng = ServingEngine(model, params, cfg, logger=lg)
        real_prefill = eng._prefill_fn
        boom = {"armed": True}

        def flaky_prefill(*a, **kw):
            if boom.pop("armed", False):
                raise RuntimeError("transient XLA prefill failure")
            return real_prefill(*a, **kw)

        eng._prefill_fn = flaky_prefill
        req = eng.submit([1, 2, 3, 4], 8)
        eng.drain()
        lg.close()
        assert req.status == "ok"
        assert eng.restarts == 1
        assert eng.pool.blocks_in_use == 0
        rec = _records(str(path), "request")[0]
        names = [e[0] for e in rec["events"]]
        assert "admission_aborted" in names
        comps = sum(rec[k] for k in TestLatencyAttribution.COMPONENTS)
        assert comps == pytest.approx(rec["lat_s"], abs=1e-3)


class TestTickRecords:
    def test_schema_gate(self, preempt_run, chaos_run):
        from tiny_deepspeed_tpu.telemetry import schema
        for path in (preempt_run[0], chaos_run[0]):
            counts, errs = schema.validate_file(path)
            assert errs == [], errs[:5]
            assert counts["meta"] > 0

    def test_wall_split_bounded_by_tick_wall(self, preempt_run):
        ticks = _records(preempt_run[0], "tick")
        assert ticks
        for t in ticks:
            parts = (t["sched_s"] + t["prefill_s"] + t["decode_s"]
                     + t["fetch_s"])
            # sched_s is the clamped remainder, so the sum can only
            # undershoot the wall by clock granularity, never overshoot
            assert parts <= t["wall_s"] + 2e-3, t
            assert parts >= 0.9 * t["wall_s"] - 2e-3, t

    def test_eventful_ticks_always_emit_quiet_ticks_sampled(
            self, model, params, tmp_path):
        """Emission policy: with tick_record_every=0 ONLY eventful ticks
        (admission/eviction here) write records — a long quiet decode
        stretch adds nothing to the file."""
        from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
        path = tmp_path / "quiet.jsonl"
        cfg = ServeConfig(max_active=2, num_blocks=16, block_tokens=8,
                          max_seq_tokens=32, tick_record_every=0)
        lg = _logger(path, cfg)
        eng = ServingEngine(model, params, cfg, logger=lg)
        eng.submit([1, 2, 3, 4], 16)
        n_ticks = 0
        while eng.n_active or eng.queue_depth:
            eng.tick()
            n_ticks += 1
        lg.close()
        ticks = _records(path, "tick")
        # admission tick + eviction tick are eventful; the ~14 decode
        # ticks in between stay silent
        assert n_ticks > 4
        assert 1 <= len(ticks) <= 3, (n_ticks, len(ticks))
        assert all(t["emit"] == "event" for t in ticks)

    def test_counts_match_engine(self, chaos_run):
        """tick_record_every=1 records EVERY tick, so the per-tick
        counters must total the engine's cumulative story exactly."""
        path, reqs, eng = chaos_run
        ticks = _records(path, "tick")
        assert sum(t["quarantined"] for t in ticks) == 1
        assert sum(t["restarted"] for t in ticks) == 1
        assert sum(t["produced"] for t in ticks) == sum(
            len(r.tokens) for r in reqs)
        occ = [t["occupancy"] for t in ticks]
        assert all(0.0 <= o <= 1.0 for o in occ)


class TestServingFlightRecorder:
    def test_flush_on_restart_covers_leadup(self, chaos_run):
        """The restart pin: the flight record's ring ends AT the restart
        tick and carries the ticks leading up to it."""
        path, _, _ = chaos_run
        flights = _records(path, "flight")
        restarts = [f for f in flights if f["reason"] == "serve_restart"]
        assert len(restarts) == 1
        fl = restarts[0]
        steps = fl["steps"]
        assert steps, "empty flight ring on a restart"
        assert steps[-1]["step"] == fl["at_step"]
        # the lead-up: the admission tick BEFORE the poisoned tick is in
        # the ring too (capacity 64 >> run length, nothing evicted)
        assert steps[0]["step"] < fl["at_step"]
        # ring entries carry the tick state + wall split
        assert "health" in steps[-1] and "segments" in steps[-1]
        assert steps[-1]["health"]["quarantined"] >= 1

    def test_quarantine_outranked_by_restart_same_tick(self, chaos_run):
        """One tick, two triggers (quarantine + watchdog restart): ONE
        flush, named after the graver trigger."""
        path, _, _ = chaos_run
        flights = _records(path, "flight")
        reasons = [f["reason"] for f in flights]
        assert "serve_restart" in reasons
        assert "serve_quarantine" not in reasons


class TestServingTraceExport:
    @pytest.fixture(scope="class")
    def trace_doc(self, chaos_run, tmp_path_factory):
        out = str(tmp_path_factory.mktemp("serveobs") / "chaos.trace.json")
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_view.py"),
             chaos_run[0], "-o", out],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        # STRICT parse (json.load raises on NaN-bearing output Perfetto
        # would reject)
        with open(out) as f:
            return json.load(f)

    def test_slot_and_queue_tracks_present(self, trace_doc):
        names = {e["args"]["name"] for e in trace_doc["traceEvents"]
                 if e.get("name") == "thread_name"}
        assert {"queue", "slot 0", "slot 1",
                "scheduler ticks", "tick wall split"} <= names

    def test_quarantine_and_restart_visible(self, trace_doc):
        insts = [e["name"] for e in trace_doc["traceEvents"]
                 if e.get("ph") == "i"]
        assert any("quarantine" in n for n in insts), insts
        assert any("restart" in n for n in insts), insts
        # the quarantined request's active window closes with the reason
        closed = [e["args"].get("window")
                  for e in trace_doc["traceEvents"]
                  if e.get("ph") == "X" and "args" in e]
        assert "quarantined" in closed

    def test_segment_spans_sum_within_tick_walls(self, trace_doc):
        """Per tick: the laid-out sched/prefill/decode/fetch spans sum
        to within the measured tick wall (their widths are measured,
        only the position inside the tick is schematic)."""
        ev = trace_doc["traceEvents"]
        ticks = [e for e in ev if e.get("ph") == "X"
                 and str(e.get("name", "")).startswith("tick ")]
        segs = [e for e in ev if e.get("ph") == "X"
                and e.get("args", {}).get("schematic_position")]
        assert ticks and segs
        for t in ticks:
            inside = [s for s in segs
                      if t["ts"] - 1 <= s["ts"] <= t["ts"] + t["dur"] + 1]
            if not inside:
                continue
            assert sum(s["dur"] for s in inside) <= t["dur"] + 2e3, t

    def test_queue_and_slot_walls_positive(self, trace_doc):
        spans = [e for e in trace_doc["traceEvents"]
                 if e.get("ph") == "X"
                 and str(e.get("name", "")).startswith("req ")]
        assert spans
        assert all(s["dur"] >= 0 for s in spans)


class TestDashboards:
    def test_serve_report_names_tail_component(self, chaos_run):
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "serve_report.py"),
             chaos_run[0]],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        md = r.stdout
        assert "Tail attribution" in md
        assert "p99 verdict" in md
        for label in ("queue-wait", "prefill", "decode-active",
                      "preempted-wait", "restart-overhead"):
            assert label in md
        assert "Flight records" in md and "serve_restart" in md

    def test_report_run_serving_section_and_check(self, preempt_run):
        path = preempt_run[0]
        for args, want_rc in ((["--check", path], 0), ([path], 0)):
            r = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "scripts", "report_run.py")] + args,
                capture_output=True, text=True, timeout=120,
            )
            assert r.returncode == want_rc, (args, r.stderr[-1500:])
        assert "## Serving" in r.stdout
        assert "serve_report.py" in r.stdout

    def test_serve_report_rejects_training_only_file(self, tmp_path):
        path = tmp_path / "train.jsonl"
        path.write_text(json.dumps(
            {"kind": "run_meta", "ts": 0.0, "engine": "DDP"}) + "\n"
            + json.dumps({"step": 0, "ts": 1.0, "loss": 2.0}) + "\n")
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "serve_report.py"),
             str(path)],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 2
        assert "no serving records" in r.stderr


class TestWireLinkSplit:
    """ICI-vs-DCN ledger split (ROADMAP satellite): cross-slice bytes
    measured from the compiled replica_groups on a CPU-emulated 2-slice
    mesh — a pinned number, not a model."""

    def test_group_membership_parser(self):
        from tiny_deepspeed_tpu.utils.hlo_comm import _group_members
        assert _group_members(
            "x replica_groups={{0,1},{2,3}} y") == ((0, 1), (2, 3))
        assert _group_members(
            "x replica_groups=[2,4]<=[8] y") == ((0, 1, 2, 3),
                                                 (4, 5, 6, 7))
        # transposed iota: groups stride across the leading dim
        assert _group_members(
            "x replica_groups=[4,2]<=[2,4]T(1,0) y") == (
            (0, 4), (1, 5), (2, 6), (3, 7))
        # 1-D iota = one group of everybody
        assert _group_members(
            "x replica_groups=[8]<=[8] y") == (
            (0, 1, 2, 3, 4, 5, 6, 7),)
        assert _group_members("x no groups here y") is None

    def test_two_slice_mesh_split_pins_dcn_bytes(self):
        """On an emulated 2-slice (4+4) mesh: a model-axis psum (groups
        {0..3},{4..7}) stays intra-slice -> ICI; a data-axis psum
        (groups {0,4},{1,5},...) spans slices -> ALL its wire bills to
        DCN.  The split is read off the compiled HLO's replica_groups,
        so the numbers equal the ledger's per-op wire exactly."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from tiny_deepspeed_tpu.parallel.mesh import make_mesh
        from tiny_deepspeed_tpu.utils.hlo_comm import (
            collective_ledger, ledger_summary, wire_link_split,
        )
        if jax.device_count() < 8:
            pytest.skip("needs 8 emulated CPU devices")
        mesh = make_mesh((2, 4), ("data", "model"))
        gmap = {i: i // 4 for i in range(8)}  # two slices of four
        x = jnp.ones((8, 8), jnp.float32)

        intra = jax.jit(shard_map(
            lambda a: jax.lax.psum(a, "model"), mesh=mesh,
            in_specs=P("data", "model"), out_specs=P("data")))
        led = collective_ledger(intra.lower(x).compile().as_text())
        split = wire_link_split(led, gmap)
        assert split["dcn_wire_bytes"] == 0.0
        assert split["ici_wire_bytes"] == pytest.approx(
            led["wire_bytes"]["all-reduce"])
        assert split["unresolved_wire_bytes"] == 0.0

        cross = jax.jit(shard_map(
            lambda a: jax.lax.psum(a, "data"), mesh=mesh,
            in_specs=P("data", "model"), out_specs=P(None, "model")))
        led = collective_ledger(cross.lower(x).compile().as_text())
        split = wire_link_split(led, gmap)
        assert split["ici_wire_bytes"] == 0.0
        assert split["dcn_wire_bytes"] == pytest.approx(
            led["wire_bytes"]["all-reduce"])
        assert split["dcn_frac"] == 1.0
        # the run_meta form carries the same split
        summ = ledger_summary(led, granule_of=gmap)
        assert summ["wire_bytes_by_link"]["dcn_wire_bytes"] \
            == split["dcn_wire_bytes"]

    # tier-1 budget: the DDP engine compile (~5s) re-checks WIRING only —
    # the split math + 2-slice classification stay quick above, and the
    # gauge NAME stays pinned by the hygiene grep (test_repo_hygiene)
    @pytest.mark.slow
    def test_capture_compiled_gauges_dcn(self, tmp_path):
        """Telemetry wiring: capture_compiled with an (emulated) granule
        map documents cross-slice bytes as the dcn_wire_bytes gauge and
        embeds the split in comm_measured — the DDP grad all-reduce
        spans the whole data axis, so under a 2-slice emulation ALL its
        wire is DCN-crossing."""
        from tiny_deepspeed_tpu import AdamW, DDP, Telemetry
        if jax.device_count() < 8:
            pytest.skip("needs 8 emulated CPU devices")
        model = GPT2Model(GPTConfig(
            block_size=32, vocab_size=128, n_layer=2, n_head=2,
            n_embd=32, compute_dtype=jnp.float32))
        telem = Telemetry()
        eng = DDP(model, AdamW(lr=1e-3), telemetry=telem)
        state = eng.init(jax.random.PRNGKey(0))
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        batch = (jax.random.randint(k1, (8, 32), 0, 128),
                 jax.random.randint(k2, (8, 32), 0, 128))
        gmap = {i: i // 4 for i in range(8)}
        out = telem.capture_compiled(state, batch, granule_of=gmap)
        split = out["comm_measured"]["wire_bytes_by_link"]
        assert split["dcn_wire_bytes"] > 0.0
        assert telem.gauge("dcn_wire_bytes") == pytest.approx(
            split["dcn_wire_bytes"])
        # the data-axis gradient reduction is what crosses
        assert split["dcn_wire_bytes"] == pytest.approx(
            out["comm_measured"]["wire_bytes"]["all-reduce"]
            + out["comm_measured"]["wire_bytes"].get(
                "reduce-scatter", 0.0), rel=0.01)
