# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""GPT-2 model tests: shapes, loss sanity, determinism, attention switch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute composition suite (see pytest.ini)

from tiny_deepspeed_tpu import GPTConfig, GPT2Model


TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)


class TestGPT2:
    def test_param_count_124m(self):
        model = GPT2Model(GPTConfig())  # default = GPT-2 124M w/ padded vocab
        n = model.num_params()
        # 124M-class: wte+wpe+blocks+lm_head (untied) with vocab padded to
        # 50304; reference model is the same shape family.
        assert 120e6 < n < 220e6

    def test_forward_loss_near_uniform(self):
        model = GPT2Model(TINY)
        params = model.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 128)
        loss = model.apply(params, idx, tgt)
        # fresh init => loss ~ ln(vocab)
        assert abs(float(loss) - np.log(128)) < 0.5

    def test_logits_shape_inference(self):
        model = GPT2Model(TINY)
        params = model.init(jax.random.PRNGKey(0))
        idx = jnp.zeros((3, 10), jnp.int32)
        logits = model.apply(params, idx)
        assert logits.shape == (3, 1, 128)

    def test_deterministic(self):
        model = GPT2Model(TINY)
        params = model.init(jax.random.PRNGKey(0))
        idx = jnp.ones((1, 16), jnp.int32)
        tgt = jnp.ones((1, 16), jnp.int32)
        a = model.apply(params, idx, tgt)
        b = model.apply(params, idx, tgt)
        assert float(a) == float(b)

    def test_attention_impls_agree(self):
        cfg_std = GPTConfig(**{**TINY.__dict__, "attn_impl": "standard_attention"})
        cfg_fla = GPTConfig(**{**TINY.__dict__, "attn_impl": "flash_attention"})
        m1, m2 = GPT2Model(cfg_std), GPT2Model(cfg_fla)
        params = m1.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 128)
        np.testing.assert_allclose(
            m1.apply(params, idx, tgt), m2.apply(params, idx, tgt),
            rtol=1e-4, atol=1e-4,
        )

    def test_scan_unroll_matches_scanned(self):
        """scan_unroll is a pure scheduling knob: fully unrolling the layer
        scan must not change the forward loss or any gradient (same math,
        same order — only the stacked-stash addressing changes)."""
        import dataclasses
        m_scan = GPT2Model(TINY)
        m_unroll = GPT2Model(dataclasses.replace(TINY, scan_unroll=True))
        params = m_scan.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 128)
        l1, g1 = jax.value_and_grad(lambda p: m_scan.apply(p, idx, tgt))(params)
        l2, g2 = jax.value_and_grad(lambda p: m_unroll.apply(p, idx, tgt))(params)
        assert np.allclose(float(l1), float(l2), rtol=1e-6)
        for k in g1:
            np.testing.assert_allclose(
                np.asarray(g1[k], np.float32), np.asarray(g2[k], np.float32),
                rtol=2e-5, atol=2e-6, err_msg=k)

    def test_block_size_enforced(self):
        model = GPT2Model(TINY)
        params = model.init(jax.random.PRNGKey(0))
        idx = jnp.zeros((1, 64), jnp.int32)
        try:
            model.apply(params, idx)
            assert False, "expected ValueError"
        except ValueError:
            pass

    def test_wte_max_norm_caps_used_rows(self):
        """max_norm renorm wired through the forward (reference
        nn.Embedding max_norm via ops/embedding.py:67-68): the gathered
        token vectors come from a row-capped table, params untouched."""
        import dataclasses
        from tiny_deepspeed_tpu.ops.embedding import renorm_weight
        cfg = dataclasses.replace(TINY, wte_max_norm=0.05)
        model = GPT2Model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        # make some rows exceed the cap
        params["wte"] = params["wte"] * 100.0
        idx = jnp.arange(32)[None, :] % cfg.vocab_size
        x = model.embed(params, idx)
        pos = params["wpe"][:32]
        tok = x[0] - pos  # undo position add
        norms = jnp.linalg.norm(tok, axis=-1)
        assert float(norms.max()) <= 0.05 * 1.01
        # stored table unchanged (functional renorm, not in-place)
        assert float(jnp.abs(params["wte"]).max()) > 1.0
        # loss path still works and differentiates
        tgt = jnp.zeros_like(idx)
        g = jax.grad(lambda p: model.apply(p, idx, tgt))(params)
        assert float(jnp.abs(g["wte"]).sum()) > 0
        del renorm_weight

    def test_grads_flow_to_all_params(self):
        model = GPT2Model(TINY)
        params = model.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 128)
        grads = jax.grad(lambda p: model.apply(p, idx, tgt))(params)
        for name, g in grads.items():
            assert bool(jnp.any(g != 0)), f"zero grad for {name}"


class TestGenerate:
    """Autoregressive sampling API (no reference counterpart — its model
    only trains; models/gpt2.py generate())."""

    def _model(self):
        from tiny_deepspeed_tpu import GPT2Model, GPTConfig
        cfg = GPTConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2,
                        n_embd=16, compute_dtype=jnp.float32)
        m = GPT2Model(cfg)
        return m, m.init(jax.random.PRNGKey(0))

    def test_shapes_and_prompt_preserved(self):
        m, params = self._model()
        idx = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        out = m.generate(params, idx, 5, key=jax.random.PRNGKey(1))
        assert out.shape == (2, 8)
        np.testing.assert_array_equal(np.asarray(out[:, :3]),
                                      np.asarray(idx))
        assert int(jnp.max(out)) < m.config.vocab_size

    def test_greedy_is_deterministic(self):
        m, params = self._model()
        idx = jnp.array([[7, 8]], jnp.int32)
        a = m.generate(params, idx, 6, temperature=0.0)
        b = m.generate(params, idx, 6, temperature=0.0,
                       key=jax.random.PRNGKey(99))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_padding_is_inert(self):
        """Greedy continuation must not depend on buffer slack beyond the
        prompt (causality + zero-pad discipline)."""
        m, params = self._model()
        idx = jnp.array([[7, 8, 9]], jnp.int32)
        out_a = m.generate(params, idx, 2, temperature=0.0)
        # same prompt, one fewer free slot used: first new token must agree
        out_b = m.generate(params, idx, 1, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(out_a[:, :4]),
                                      np.asarray(out_b))

    def test_rejects_overflow(self):
        m, params = self._model()
        idx = jnp.zeros((1, 30), jnp.int32)
        with pytest.raises(ValueError, match="block_size"):
            m.generate(params, idx, 5)

    def test_requires_key_for_sampling(self):
        m, params = self._model()
        idx = jnp.array([[1, 2]], jnp.int32)
        with pytest.raises(ValueError, match="PRNG key"):
            m.generate(params, idx, 2)  # temperature=1.0, no key

    def test_jit_cache_reused(self):
        m, params = self._model()
        idx = jnp.array([[1, 2]], jnp.int32)
        m.generate(params, idx, 3, temperature=0.0)
        assert len(m._generate_cache) == 1
        m.generate(params, idx, 3, temperature=0.0)
        assert len(m._generate_cache) == 1  # same shapes -> no new trace

    def test_moe_generate(self):
        from tiny_deepspeed_tpu import MoEConfig, MoEGPT
        cfg = MoEConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2,
                        n_embd=16, n_expert=2, compute_dtype=jnp.float32)
        m = MoEGPT(cfg)
        params = m.init(jax.random.PRNGKey(0))
        idx = jnp.array([[1, 2, 3]], jnp.int32)
        out = m.generate(params, idx, 4, temperature=0.0)
        assert out.shape == (1, 7)
        np.testing.assert_array_equal(np.asarray(out[:, :3]),
                                      np.asarray(idx))


class TestBiasAndDropout:
    """Reference-parity config knobs (reference example/model.py:23-24).
    NB the reference's own dropout wiring is dead code — it hard-codes
    `dropout_p=False` at every call site (model.py:79-81) — so behavior
    here is what the knob *means*, not what the reference does."""

    CFG = dict(block_size=32, vocab_size=128, n_layer=2, n_head=2,
               n_embd=32, compute_dtype=jnp.float32)

    def test_bias_false_drops_projection_biases_only(self):
        m = GPT2Model(GPTConfig(bias=False, **self.CFG))
        p = m.init(jax.random.PRNGKey(0))
        for name in ("h.attn.qkv.b", "h.attn.proj.b",
                     "h.mlp.fc.b", "h.mlp.proj.b"):
            assert name not in p
        # layernorm biases stay (reference uses stock nn.LayerNorm)
        assert "h.ln_1.b" in p and "ln_f.b" in p
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        assert float(m.apply(p, idx, idx)) > 0

    def test_bias_false_trains(self):
        from tiny_deepspeed_tpu import AdamW, Zero3
        m = GPT2Model(GPTConfig(bias=False, **self.CFG))
        eng = Zero3(m, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        losses = []
        for i in range(3):
            k1, k2 = jax.random.split(jax.random.PRNGKey(100 + i))
            batch = (jax.random.randint(k1, (8, 32), 0, 128),
                     jax.random.randint(k2, (8, 32), 0, 128))
            state, loss = eng.step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_dropout_train_eval_semantics(self):
        m = GPT2Model(GPTConfig(dropout=0.2, **self.CFG))
        p = m.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
        # eval (no rng): deterministic and identical to a dropout=0 model
        m0 = GPT2Model(GPTConfig(**self.CFG))
        assert float(m.apply(p, idx, idx)) == float(m0.apply(p, idx, idx))
        # train: same key reproduces, different keys differ
        la = float(m.apply(p, idx, idx, rng=jax.random.PRNGKey(5)))
        lb = float(m.apply(p, idx, idx, rng=jax.random.PRNGKey(6)))
        lc = float(m.apply(p, idx, idx, rng=jax.random.PRNGKey(5)))
        assert la == lc and la != lb

    def test_dropout_engine_trains_and_differs_from_eval(self):
        from tiny_deepspeed_tpu import AdamW, SingleDevice
        m = GPT2Model(GPTConfig(dropout=0.1, **self.CFG))
        m0 = GPT2Model(GPTConfig(**self.CFG))
        batch = (jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128),
                 jax.random.randint(jax.random.PRNGKey(2), (8, 32), 0, 128))
        e1 = SingleDevice(m, AdamW(lr=1e-3))
        e0 = SingleDevice(m0, AdamW(lr=1e-3))
        s1, l1 = e1.step(e1.init(jax.random.PRNGKey(0)), batch)
        s0, l0 = e0.step(e0.init(jax.random.PRNGKey(0)), batch)
        assert float(l1) != float(l0)  # masks actually applied
        assert abs(float(l1) - float(l0)) < 1.0  # but sane

    def test_dropout_composes_with_pipeline(self):
        from tiny_deepspeed_tpu import AdamW, Zero1
        cfg = dict(self.CFG, n_layer=4, n_embd=64)
        m = GPT2Model(GPTConfig(dropout=0.1, **cfg))
        eng = Zero1(m, AdamW(lr=1e-3), pipeline_parallel=2)
        state = eng.init(jax.random.PRNGKey(0))
        batch = (jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128),
                 jax.random.randint(jax.random.PRNGKey(2), (8, 32), 0, 128))
        state, loss = eng.step(state, batch)
        assert 0 < float(loss) < 20

    def test_knobs_cover_moe_family(self):
        """bias/dropout extend to MoEGPT (review r2: the knobs must not be
        GPT-2-only — MoEConfig inherits them)."""
        from tiny_deepspeed_tpu import AdamW, MoEConfig, MoEGPT, SingleDevice
        cfg = MoEConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2,
                        n_embd=32, n_expert=2, compute_dtype=jnp.float32,
                        bias=False, dropout=0.2)
        m = MoEGPT(cfg)
        p = m.init(jax.random.PRNGKey(0))
        for name in ("h.attn.qkv.b", "h.attn.proj.b",
                     "h.moe.fc.b", "h.moe.proj.b"):
            assert name not in p
        idx = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        la = float(m.apply(p, idx, idx, rng=jax.random.PRNGKey(5)))
        lb = float(m.apply(p, idx, idx, rng=jax.random.PRNGKey(6)))
        assert la != lb  # masks actually drawn
        eng = SingleDevice(m, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        state, loss = eng.step(state, (idx[:2], idx[:2]))
        assert 0 < float(loss) < 20

    def test_knobs_cover_llama_family(self):
        """dropout extends to LlamaModel's residual sites (not just the
        shared embedding dropout)."""
        from tiny_deepspeed_tpu import LlamaConfig, LlamaModel
        cfg = LlamaConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2,
                          n_embd=32, compute_dtype=jnp.float32, dropout=0.5)
        m = LlamaModel(cfg)
        p = m.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
        # embedding dropout alone cannot explain a per-LAYER key effect:
        # compare against a model whose blocks ignore dropout_rng by
        # stripping the keys after setup — losses must differ
        la = float(m.apply(p, idx, idx, rng=jax.random.PRNGKey(5)))
        stacked = m.stacked_compute_params(p)
        x = m.embed(p, idx)
        stacked2, x2 = m._dropout_setup(stacked, x, jax.random.PRNGKey(5))
        stacked2.pop("dropout_rng")  # keep embedding dropout only
        import jax.numpy as _jnp
        block = m.block_fn(None)
        y, _ = jax.lax.scan(lambda c, bp: (block(c, bp), None), x2, stacked2)
        lb = float(m.head(p, y, idx))
        assert la != lb
        assert float(m.apply(p, idx, idx)) > 0  # eval path intact


class TestKVCacheDecode:
    """generate(use_cache=True): prefill + single-position cached decode.
    Greedy outputs must EQUAL the uncached full-forward path — the cache is
    an execution strategy, not a semantic change."""

    CFG = dict(block_size=64, vocab_size=128, n_layer=2, n_head=2,
               n_embd=32, compute_dtype=jnp.float32)

    def _greedy_both(self, m, vocab=128, t0=7, new=12):
        p = m.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, t0), 0, vocab)
        a = m.generate(p, idx, new, temperature=0.0, use_cache=False)
        b = m.generate(p, idx, new, temperature=0.0, use_cache=True)
        return np.asarray(a), np.asarray(b)

    def test_gpt2_cached_equals_uncached(self):
        a, b = self._greedy_both(GPT2Model(GPTConfig(**self.CFG)))
        np.testing.assert_array_equal(a, b)

    def test_gpt2_nobias_cached_equals_uncached(self):
        a, b = self._greedy_both(
            GPT2Model(GPTConfig(bias=False, **self.CFG))
        )
        np.testing.assert_array_equal(a, b)

    def test_moe_cached_equals_uncached(self):
        from tiny_deepspeed_tpu import MoEConfig, MoEGPT
        cfg = MoEConfig(n_expert=2, **self.CFG)
        a, b = self._greedy_both(MoEGPT(cfg))
        np.testing.assert_array_equal(a, b)

    def test_llama_gqa_cached_equals_uncached(self):
        from tiny_deepspeed_tpu import LlamaConfig, LlamaModel
        cfg = LlamaConfig(block_size=64, vocab_size=128, n_layer=2,
                          n_head=4, n_kv_head=2, n_embd=32,
                          compute_dtype=jnp.float32)
        a, b = self._greedy_both(LlamaModel(cfg))
        np.testing.assert_array_equal(a, b)

    def test_sampled_decode_runs_and_caches_jit(self):
        m = GPT2Model(GPTConfig(**self.CFG))
        p = m.init(jax.random.PRNGKey(0))
        idx = jnp.array([[1, 2, 3]], jnp.int32)
        out = m.generate(p, idx, 5, temperature=0.8, top_k=20,
                         key=jax.random.PRNGKey(7))
        assert out.shape == (1, 8)
        n = len(m._generate_cache)
        m.generate(p, idx, 5, temperature=0.8, top_k=20,
                   key=jax.random.PRNGKey(8))
        assert len(m._generate_cache) == n  # same shapes -> no new trace

    def test_moe_many_experts_small_batch(self):
        """Review r2: decode routes S=B tokens, so the train-time capacity
        formula would collapse to 1 slot at E=8/B=2; the decode path uses
        the drop-free S*k capacity instead.  Bit-equality with the uncached
        path is NOT expected here — the full-sequence path's static
        capacity drops over-capacity tokens that drop-free decode keeps
        (inherent to GShard routing; the generate() docstring scopes the
        equality claim accordingly).  The invariants that DO hold: the
        prompt is preserved, decode is deterministic, and tokens are in
        range."""
        from tiny_deepspeed_tpu import MoEConfig, MoEGPT
        cfg = MoEConfig(n_expert=8, expert_top_k=2, **self.CFG)
        m = MoEGPT(cfg)
        p = m.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, 128)
        a = np.asarray(m.generate(p, idx, 12, temperature=0.0))
        b = np.asarray(m.generate(p, idx, 12, temperature=0.0))
        np.testing.assert_array_equal(a, b)  # deterministic
        np.testing.assert_array_equal(a[:, :7], np.asarray(idx))
        assert ((0 <= a) & (a < 128)).all()


class TestWeightTying:
    """tie_weights=True: lm_head projects through wte.T (actual GPT-2 ties;
    the reference unties, model.py:136-138, so False is the default)."""

    CFG = dict(block_size=64, vocab_size=128, n_layer=2, n_head=2,
               n_embd=32, compute_dtype=jnp.float32)

    @pytest.mark.parametrize("family", ["gpt2", "moe", "llama"])
    def test_tied_param_set_and_training(self, family):
        from tiny_deepspeed_tpu import (
            AdamW, LlamaConfig, LlamaModel, MoEConfig, MoEGPT, SingleDevice,
        )
        if family == "gpt2":
            m = GPT2Model(GPTConfig(tie_weights=True, **self.CFG))
        elif family == "moe":
            m = MoEGPT(MoEConfig(tie_weights=True, n_expert=2, **self.CFG))
        else:
            m = LlamaModel(LlamaConfig(tie_weights=True, **self.CFG))
        p = m.init(jax.random.PRNGKey(0))
        assert "lm_head.w" not in p
        eng = SingleDevice(m, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        # fixed batch: loss must drop when stepping on the same data
        k1, k2 = jax.random.split(jax.random.PRNGKey(100))
        batch = (jax.random.randint(k1, (8, 64), 0, 128),
                 jax.random.randint(k2, (8, 64), 0, 128))
        losses = []
        for _ in range(4):
            state, loss = eng.step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_tied_saves_params_and_generates(self):
        untied = GPT2Model(GPTConfig(**self.CFG))
        tied = GPT2Model(GPTConfig(tie_weights=True, **self.CFG))
        nu, nt = untied.num_params(), tied.num_params()
        assert nu - nt == 128 * 32  # exactly the lm_head table
        p = tied.init(jax.random.PRNGKey(0))
        idx = jnp.array([[1, 2, 3]], jnp.int32)
        a = tied.generate(p, idx, 6, temperature=0.0, use_cache=True)
        b = tied.generate(p, idx, 6, temperature=0.0, use_cache=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_tied_grad_flows_through_both_uses(self):
        """d(loss)/d(wte) must include the lm_head contribution: zeroing
        targets' wte rows still leaves nonzero grad via the projection."""
        m = GPT2Model(GPTConfig(tie_weights=True, **self.CFG))
        p = m.init(jax.random.PRNGKey(0))
        idx = jnp.zeros((2, 8), jnp.int32)  # only token 0 gathered
        tgt = jnp.full((2, 8), 5, jnp.int32)
        g = jax.grad(lambda p: m.apply(p, idx, tgt))(p)
        # rows never gathered (e.g. 100) get grad ONLY via the projection
        assert float(jnp.abs(g["wte"][100]).sum()) > 0
