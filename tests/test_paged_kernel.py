# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Raw-speed kernels + the end-to-end autotuner (ISSUE 14).

Acceptance pins:
  * the Pallas paged-attention kernel (ops/paged_attn_pallas.py, run in
    interpret mode on the CPU CI mesh) matches the XLA reference —
    `paged_panel` + `_decode_attention` / `_span_attention` — to float
    tolerance on random pool contents, GQA and quantized pools
    included, and is greedy TOKEN-IDENTICAL through a real
    ServingEngine staggered-admission trace (plain decode AND the
    spec-verify span variant);
  * kernel-off paths stay byte-identical: `paged_kernel="off"` lowers
    the same HLO as the default CPU path, and the fp8 matmul mode
    "off" leaves `linear_forward`'s lowering untouched;
  * fp8 matmuls (ops/matmul_fp8.py): e4m3 numerics within quantization
    tolerance, delayed-scaling history semantics, candidate-list
    gating, and the 20-step training loss parity (<5%) the gather_quant
    precedent set (slow tier);
  * tune_e2e: coordinate-descent mechanics (bool-vs-int knob identity,
    failure tolerance, objective direction), plan persistence through
    the AOT cache's v2 envelope (legacy flat files still load), and
    the spec_k round-trip — a tuned plan's spec_k reaches ServeConfig
    through bench.resolve_spec_k and flips `_config_fingerprint`;
  * autotuner diagnostics land in the Telemetry registry / MetricsLogger
    (run_meta records, candidate-failure counter+gauge) instead of
    bare prints;
  * scripts/tier1_times.py --budget output stays asserted (the CI gate
    this suite's own additions are budgeted against).

Budget note: tier-1 headroom is under a minute on the 2-vCPU box, so
every multi-engine trace here is slow-marked from the start; the quick
tier keeps one numeric-parity pin and one wiring pin per kernel.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tiny_deepspeed_tpu.ops.paged_attn_pallas as PAP
from tiny_deepspeed_tpu import GPTConfig, GPT2Model
from tiny_deepspeed_tpu.ops import matmul_fp8 as MF
from tiny_deepspeed_tpu.serving.pool import (
    PagedKVPool, page_ref, paged_panel,
)

CFG = dict(block_size=64, vocab_size=128, n_layer=2, n_head=2,
           n_embd=32, compute_dtype=jnp.float32)


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setattr(PAP, "INTERPRET", True)


@pytest.fixture(scope="module")
def model():
    return GPT2Model(GPTConfig(**CFG))


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0))


def _pool_view(quant, kvh=2, dh=16, L=2, bt=8, blocks=16):
    """A pool whose blocks hold random content (quantized through the
    real codec when quant is set)."""
    pool = PagedKVPool(n_layer=L, kv_heads=kvh, head_dim=dh,
                      num_blocks=blocks, block_tokens=bt,
                      dtype=jnp.float32, quant=quant)
    view = pool.view
    k1, k2 = jax.random.split(jax.random.PRNGKey(7))
    raw_k = jax.random.normal(k1, view.k.shape, jnp.float32)
    raw_v = jax.random.normal(k2, view.v.shape, jnp.float32)
    if quant:
        from tiny_deepspeed_tpu.serving.pool import _quant_vectors
        qk, sk = _quant_vectors(raw_k, quant)
        qv, sv = _quant_vectors(raw_v, quant)
        return view._replace(k=qk, v=qv, k_scale=sk, v_scale=sv)
    return view._replace(k=raw_k, v=raw_v)


_TABLES = [[1, 2, 3, 0], [4, 5, 0, 0], [6, 0, 0, 0]]


class TestPagedKernelParity:
    """Kernel numerics vs the XLA reference on the same pool operands."""

    # quick tier carries ONE representative case (GQA + int8: the
    # grouped heads AND the in-kernel dequant in one pin); the full
    # matrix is slow-marked per the tier-1 zero-sum budget rule
    @pytest.mark.parametrize("quant,hq", [
        ("int8", 4),
        pytest.param(None, 2, marks=pytest.mark.slow),
        pytest.param(None, 4, marks=pytest.mark.slow),
        pytest.param("int8", 2, marks=pytest.mark.slow),
        pytest.param("fp8", 2, marks=pytest.mark.slow),
        pytest.param("fp8", 4, marks=pytest.mark.slow),
    ])
    def test_decode_matches_xla(self, model, quant, hq):
        view = _pool_view(quant)
        tables = jnp.asarray(_TABLES, jnp.int32)
        pos = jnp.asarray([25, 9, 0], jnp.int32)  # mid/partial/first token
        page = page_ref(tables, pos, 8)
        q = jax.random.normal(jax.random.PRNGKey(3), (3, hq, 1, 16),
                              jnp.float32)
        for layer in range(2):
            ck, cv = paged_panel(view, layer, page, jnp.float32)
            ref = model._decode_attention(q, ck, cv, pos)
            got = PAP.paged_attention(q, view, page, layer)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("quant", [
        None, pytest.param("int8", marks=pytest.mark.slow)])
    def test_span_matches_xla_incl_empty_prefix(self, model, quant):
        """Span-verify variant vs `_span_attention`, with one slot at
        pos0=0 (pool prefix fully masked — the online-softmax edge) and
        a traced layer index under jit+scan, exactly how paged_verify
        consumes it."""
        view = _pool_view(quant)
        k1 = 5
        tables = jnp.asarray(_TABLES, jnp.int32)
        pos0 = jnp.asarray([25, 9, 0], jnp.int32)
        page = page_ref(tables, jnp.minimum(pos0, 31), 8)._replace(pos=pos0)
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        q = jax.random.normal(ks[0], (3, 4, k1, 16), jnp.float32)
        sk = jax.random.normal(ks[1], (3, 2, k1, 16), jnp.float32)
        sv = jax.random.normal(ks[2], (3, 2, k1, 16), jnp.float32)

        def run(view, q, sk, sv, page):
            def body(c, layer):
                return c, PAP.paged_attention(q, view, page, layer,
                                              span_kv=(sk, sv))
            _, ys = jax.lax.scan(body, 0, jnp.arange(2))
            return ys

        ys = jax.jit(run)(view, q, sk, sv, page)
        for layer in range(2):
            ck, cv = paged_panel(view, layer, page, jnp.float32)
            ref = model._span_attention(q, ck, cv, sk, sv, pos0)
            np.testing.assert_allclose(np.asarray(ys[layer]),
                                       np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)

    def test_dispatch_gate(self):
        """use_paged_kernel: off/on force both ways; auto follows the
        kernel target (CPU mesh -> XLA path)."""
        from tiny_deepspeed_tpu.ops.dispatch import kernel_target_forced
        assert PAP.paged_kernel_mode() == "auto"
        assert not PAP.use_paged_kernel()  # CPU target
        with PAP.paged_kernel_forced("on"):
            assert PAP.use_paged_kernel()
            assert PAP.effective_paged_kernel() == "pallas"
        with PAP.paged_kernel_forced("off"):
            with kernel_target_forced("tpu"):
                assert not PAP.use_paged_kernel()
        with kernel_target_forced("tpu"):
            assert PAP.use_paged_kernel()
        with pytest.raises(ValueError):
            PAP.set_paged_kernel("sometimes")


def _staggered_trace(model, params, kmode, spec=None, quant=None):
    """Three requests through a real ServingEngine, the third admitted
    mid-flight; returns each request's committed tokens."""
    from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
    prompts = [
        np.asarray(jax.random.randint(jax.random.PRNGKey(s), (n,), 0, 128),
                   np.int32).tolist()
        for s, n in ((1, 7), (2, 13), (3, 9))
    ]
    eng = ServingEngine(model, params, ServeConfig(
        max_active=2, num_blocks=24, block_tokens=8, max_seq_tokens=48,
        paged_kernel=kmode, spec_draft=spec, quant=quant))
    handles = [eng.submit(prompts[0], 12), eng.submit(prompts[1], 12)]
    for _ in range(4):
        eng.tick()
    handles.append(eng.submit(prompts[2], 12))
    while not all(r.state == "done" for r in handles):
        eng.tick()
    assert all(r.status == "ok" for r in handles)
    return [r.tokens for r in handles]


class TestEngineTokenIdentity:
    """The serving contract: the kernel may change speed, never tokens."""

    def test_greedy_token_identity_staggered(self, model, params):
        """Quick wiring pin: kernel-on (interpret) vs kernel-off greedy
        decode through the real engine, staggered admission."""
        off = _staggered_trace(model, params, "off")
        on = _staggered_trace(model, params, "on")
        assert on == off

    @pytest.mark.slow
    def test_spec_span_token_identity(self, model, params):
        """The span-verify variant: a spec engine (ngram drafter) with
        the kernel on commits the same tokens as kernel-off — and the
        same tokens as the plain decode path (spec's own guarantee)."""
        off = _staggered_trace(model, params, "off", spec="ngram")
        on = _staggered_trace(model, params, "on", spec="ngram")
        plain = _staggered_trace(model, params, "off")
        assert on == off == plain

    @pytest.mark.slow
    def test_quantized_pool_token_identity(self, model, params):
        """int8 pool: kernel and XLA read the SAME quantized blocks, so
        greedy tokens stay identical between the arms."""
        off = _staggered_trace(model, params, "off", quant="int8")
        on = _staggered_trace(model, params, "on", quant="int8")
        assert on == off

    def test_bad_mode_refused(self, model, params):
        from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
        with pytest.raises(ValueError, match="paged_kernel"):
            ServingEngine(model, params,
                          ServeConfig(paged_kernel="maybe"))


class TestFp8Matmul:
    def test_numerics_within_quantization_tolerance(self):
        k = jax.random.split(jax.random.PRNGKey(0), 2)
        x = jax.random.normal(k[0], (4, 32, 64), jnp.float32)
        w = jax.random.normal(k[1], (64, 48), jnp.float32) * 0.2
        from tiny_deepspeed_tpu.ops.linear import _fwd_xla
        ref = _fwd_xla(x, w, None)
        got = MF._fwd_fp8(x, w, None)
        rel = float(jnp.max(jnp.abs(got - ref)) / jnp.max(jnp.abs(ref)))
        assert rel < 0.1  # e4m3 rowwise/colwise-scaled quantization

    def test_off_path_hlo_byte_identical(self):
        """The no-fp8 trace is the EXACT pre-fp8 program (fresh
        closures per lowering: jit's trace cache keys on function
        identity)."""
        from tiny_deepspeed_tpu.ops.linear import linear_forward
        k = jax.random.split(jax.random.PRNGKey(0), 2)
        x = jax.random.normal(k[0], (2, 16, 32), jnp.float32)
        w = jax.random.normal(k[1], (32, 8), jnp.float32)

        def make():
            def f(a, b):
                return linear_forward(a, b, None)
            return f

        t0 = jax.jit(make()).lower(x, w).as_text()
        with MF.fp8_matmul_forced("on"):
            t_on = jax.jit(make()).lower(x, w).as_text()
        t1 = jax.jit(make()).lower(x, w).as_text()
        assert t0 == t1, "fp8 'off' drifted the default lowering"
        assert t_on != t0 and "f8" in t_on

    def test_candidate_mode_gates_list(self):
        from tiny_deepspeed_tpu.autotuner import RuntimeAutoTuner
        from tiny_deepspeed_tpu.ops.linear import linear_forward
        x = jnp.ones((2, 8, 16))
        w = jnp.ones((16, 4))
        with MF.fp8_matmul_forced("candidate"):
            t = RuntimeAutoTuner(warmup=1, iters=1)
            linear_forward(x, w, None, tuner=t)
            (key, winner), = t.cache.items()
            assert any("_fwd_fp8" in n for n in key[0])
        t2 = RuntimeAutoTuner(warmup=1, iters=1)
        linear_forward(x, w, None, tuner=t2)
        (key2, _), = t2.cache.items()
        assert not any("_fwd_fp8" in n for n in key2[0])

    def test_delayed_scaling_history(self):
        """Step 0 falls back to JIT scaling (cold history); later steps
        quantize against the recorded maxima, and the history rolls."""
        k = jax.random.split(jax.random.PRNGKey(1), 2)
        x = jax.random.normal(k[0], (8, 16), jnp.float32)
        w = jax.random.normal(k[1], (16, 8), jnp.float32)
        h = MF.fp8_history(4)
        y0, h = MF.fp8_matmul_delayed(x, w, h)
        assert float(h.x_amax[0]) == pytest.approx(
            float(jnp.max(jnp.abs(x))))
        exact = np.asarray(x) @ np.asarray(w)
        rel = (np.linalg.norm(np.asarray(y0) - exact)
               / np.linalg.norm(exact))
        assert rel < 0.1  # per-tensor e4m3 quantization error envelope
        # a 2x-hotter step quantizes against the STALE amax: values
        # clip into e4m3 range instead of overflowing
        y1, h = MF.fp8_matmul_delayed(x * 2, w, h)
        assert np.all(np.isfinite(np.asarray(y1)))
        assert float(h.x_amax[0]) == pytest.approx(
            2 * float(jnp.max(jnp.abs(x))), rel=1e-6)
        assert float(h.x_amax[1]) == pytest.approx(
            float(jnp.max(jnp.abs(x))), rel=1e-6)

    def test_bad_mode_refused(self):
        with pytest.raises(ValueError, match="fp8_matmul"):
            MF.set_fp8_matmul("half")

    @pytest.mark.slow
    def test_twenty_step_loss_parity(self):
        """fp8 'on' (every linear fwd + the fused-xent head) composes
        with the real training engine: 20 AdamW steps land within 5% of
        the exact path — the gather_quant convergence precedent."""
        from tiny_deepspeed_tpu import AdamW, SingleDevice
        cfg = GPTConfig(block_size=32, vocab_size=128, n_layer=2,
                        n_head=2, n_embd=32, compute_dtype=jnp.float32,
                        fused_xent=True)

        def final_loss(mode):
            MF.set_fp8_matmul(mode)
            try:
                eng = SingleDevice(GPT2Model(cfg), AdamW(lr=1e-3))
                st = eng.init(jax.random.PRNGKey(0))
                rng = np.random.default_rng(0)
                for _ in range(20):
                    a = rng.integers(0, 128, (4, 33))
                    st, loss = eng.step(st, (
                        jnp.asarray(a[:, :-1], jnp.int32),
                        jnp.asarray(a[:, 1:], jnp.int32)))
                return float(loss)
            finally:
                MF.set_fp8_matmul("off")

        base = final_loss("off")
        f8 = final_loss("on")
        assert abs(f8 - base) / abs(base) < 0.05


class TestTuneE2E:
    def test_coordinate_descent_finds_min_and_types_distinct(self):
        from tiny_deepspeed_tpu.autotuner import tune_e2e
        seen = []

        def measure(plan):
            seen.append(dict(plan))
            cost = {1: 3.0, True: 1.0}[plan["unroll"]]
            return cost + {"off": 0.5, "on": 0.0}[plan["fp8"]]

        best, score, trials = tune_e2e(
            measure, {"unroll": [1, True], "fp8": ["off", "on"]},
            objective="min")
        assert best == {"unroll": True, "fp8": "on"} and score == 1.0
        # bool-vs-int knob values are distinct assignments (True != 1)
        assert any(p["unroll"] is True for p in seen)
        assert trials[0]["plan"] == {"unroll": 1, "fp8": "off"}
        assert len(trials) == 3

    def test_objective_max_and_failures_tolerated(self):
        from tiny_deepspeed_tpu.autotuner import tune_e2e

        def measure(plan):
            if plan["k"] == 8:
                raise RuntimeError("does not compile")
            return float(plan["k"])

        best, score, trials = tune_e2e(measure, {"k": [2, 4, 8]},
                                       objective="max")
        assert best == {"k": 4} and score == 4.0
        assert any(t["score"] is None for t in trials)  # the failed arm
        with pytest.raises(RuntimeError, match="every candidate"):
            tune_e2e(lambda p: 1 / 0, {"k": [1, 2]})

    def test_plan_persistence_v2_envelope(self, tmp_path):
        from tiny_deepspeed_tpu.autotuner import (
            RuntimeAutoTuner, plan_hash, plan_key,
        )
        t = RuntimeAutoTuner(warmup=1, iters=1)
        key = plan_key("tiny", "1dev", "cpu")
        plan = {"spec_k": 6, "scan_unroll": True}
        h = t.store_plan(key, plan, {"serve_tok_s_tuned": 123.0})
        assert h == plan_hash(plan)
        p = str(tmp_path / "cache.json")
        t.save(p)
        t2 = RuntimeAutoTuner()
        t2.load(p)
        entry = t2.get_plan(key)
        assert entry["plan"] == plan and entry["hash"] == h
        assert entry["record"]["serve_tok_s_tuned"] == 123.0
        with open(p) as f:
            assert json.load(f)["version"] == 2

    def test_legacy_flat_cache_still_loads(self, tmp_path):
        """Pre-plan AOT caches (flat {key: winner}) keep working."""
        from tiny_deepspeed_tpu.autotuner import RuntimeAutoTuner

        def fast(x):
            return x + 1.0

        def slow(x):
            return x + 1.0

        t = RuntimeAutoTuner(warmup=1, iters=1)
        x = jnp.ones((16, 16))
        t.choose([slow, fast], (x,))
        p = str(tmp_path / "legacy.json")
        # write the OLD format by hand
        flat = {json.dumps(k): fn.__module__ + "." + fn.__name__
                for k, fn in t.cache.items()}
        with open(p, "w") as f:
            json.dump(flat, f)
        t2 = RuntimeAutoTuner(warmup=1, iters=1)
        assert t2.load(p) == 1
        assert t2.choose([slow, fast], (x,)) in (slow, fast)
        assert len(t2.cache) == 1  # resolved from the store, no timing
        # and a save() round-trips it into the v2 envelope
        t2.save(p)
        t3 = RuntimeAutoTuner()
        assert t3.load(p) == 1

    def test_spec_k_roundtrip_plan_to_serveconfig_to_fingerprint(
            self, tmp_path, monkeypatch):
        """The satellite fix: a tuned spec_k round-trips plan ->
        resolve_spec_k -> ServeConfig, and the consumed plan's hash
        lands in BENCH_TUNE_PLAN so `_config_fingerprint` separates
        runs under different plans."""
        import bench
        from tiny_deepspeed_tpu.autotuner import (
            RuntimeAutoTuner, plan_key,
        )
        from tiny_deepspeed_tpu.serving import ServeConfig

        cache = str(tmp_path / "cache.json")
        monkeypatch.setenv("BENCH_TUNE_CACHE", cache)
        monkeypatch.delenv("BENCH_SPEC_K", raising=False)
        monkeypatch.delenv("BENCH_TUNE_PLAN", raising=False)
        mesh, backend = bench._mesh_desc()
        t = RuntimeAutoTuner()
        t.store_plan(plan_key("tiny", mesh, backend), {"spec_k": 6}, {})
        t.save(cache)

        fp_before = bench._config_fingerprint()
        k, source = bench.resolve_spec_k("tiny")
        assert (k, source) == (6, "plan")
        assert os.environ["BENCH_TUNE_PLAN"]  # hash exported
        assert bench._config_fingerprint() != fp_before
        cfg = ServeConfig(spec_draft="ngram", spec_k=k)
        assert cfg.spec_k == 6
        # explicit env outranks the plan
        monkeypatch.setenv("BENCH_SPEC_K", "3")
        assert bench.resolve_spec_k("tiny") == (3, "env")
        # no plan, no env -> the hand-set default
        monkeypatch.delenv("BENCH_SPEC_K")
        monkeypatch.setenv("BENCH_TUNE_CACHE", str(tmp_path / "none.json"))
        assert bench.resolve_spec_k("tiny") == (4, "default")


class TestAutotunerDiagnostics:
    """Satellite: runtime_tuner's bare prints became telemetry."""

    def test_candidate_failure_counts_and_decision_records(self, tmp_path):
        from tiny_deepspeed_tpu.autotuner import RuntimeAutoTuner
        from tiny_deepspeed_tpu.telemetry import Telemetry
        from tiny_deepspeed_tpu.utils.profiling import MetricsLogger

        def broken(x):
            raise ValueError("unsupported")

        def fine(x):
            return x + 1.0

        path = str(tmp_path / "m.jsonl")
        tel = Telemetry()
        with MetricsLogger(path, stdout=False) as ml:
            t = RuntimeAutoTuner(warmup=1, iters=1)
            t.attach_diagnostics(tel, ml)
            winner = t.choose([broken, fine], (jnp.ones((8, 8)),))
        assert winner is fine
        assert tel.counters["autotune_candidate_failures"].value == 1
        assert tel.gauges["autotune_candidate_failures"] == 1.0
        with open(path) as f:
            recs = [json.loads(line) for line in f]
        events = [r["autotune"]["event"] for r in recs if "autotune" in r]
        assert "candidate_failed" in events and "decision" in events
        dec = next(r["autotune"] for r in recs
                   if r.get("autotune", {}).get("event") == "decision")
        assert dec["winner"] == "fine"
        failed = next(e for e in dec["ranking"]
                      if e["candidate"] == "broken")
        assert failed["us"] is None

    def test_gauge_documented(self):
        from tiny_deepspeed_tpu.telemetry import schema
        assert "autotune_candidate_failures" in schema.GAUGES
        assert "autotune" in schema.META_FIELDS

    def test_record_validates_against_schema(self, tmp_path):
        """The autotune run_meta record passes report_run --check's
        field validation (schema drift would fail CI there)."""
        from tiny_deepspeed_tpu.telemetry.schema import validate_record
        err = validate_record({"kind": "run_meta", "ts": 0.0,
                               "autotune": {"event": "decision"}})
        assert not err


class TestTier1Budget:
    """Satellite: the tier-1 budget gate's output stays asserted here
    (the suite these kernels' quick pins are budgeted against)."""

    def test_budget_check_predicate(self):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        os.pardir, "scripts"))
        try:
            from tier1_times import (
                TIER1_BUDGET_S, TIER1_HEADROOM_WARN_S, budget_check,
            )
        finally:
            sys.path.pop(0)
        ok, msg = budget_check(100.0, 870.0)
        assert ok and "within budget" in msg and "headroom 770.0s" in msg
        ok, msg = budget_check(TIER1_BUDGET_S - TIER1_HEADROOM_WARN_S / 2)
        assert ok and "WARNING" in msg
        ok, msg = budget_check(900.0, 870.0)
        assert not ok and "BUDGET EXCEEDED" in msg

    def test_cli_budget_exit_codes(self, tmp_path):
        """`tier1_times.py --from-log --budget S` exits 1 past the
        budget, 0 inside it, and prints the shared message."""
        import subprocess
        import sys
        log = tmp_path / "t1.log"
        log.write_text(
            "  500.00s call     tests/test_x.py::test_a\n"
            "  100.00s call     tests/test_y.py::test_b[p0]\n"
        )
        script = os.path.join(os.path.dirname(__file__), os.pardir,
                              "scripts", "tier1_times.py")
        r = subprocess.run(
            [sys.executable, script, "--from-log", str(log),
             "--budget", "870"],
            capture_output=True, text=True)
        assert r.returncode == 0 and "within budget" in r.stdout
        r = subprocess.run(
            [sys.executable, script, "--from-log", str(log),
             "--budget", "550"],
            capture_output=True, text=True)
        assert r.returncode == 1 and "BUDGET EXCEEDED" in r.stderr
