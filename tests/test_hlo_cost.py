# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""HLO cost ledger (utils/hlo_cost.py) + perf_diff sentinel.

Three layers of pins:
  * exact dot/fusion FLOP arithmetic and while-trip multiplication on
    tiny SYNTHETIC HLO text (no compile, no jax numerics);
  * the 124M GPT-2 train step's HLO-counted matmul FLOPs within 2% of
    bench's analytic `flops_tok_matmul` — the "measured ground truth
    agrees with the honest hand formula" acceptance — and the MoE
    dispatch/combine undercount first DEMONSTRATED (counted >> the old
    formula) then CORRECTED (counted ~= formula + the new
    `dispatch_combine_flops_per_token` term);
  * scripts/perf_diff.py verdicts via its real CLI: injected 10%
    regression exits nonzero naming metric + fingerprint, identical
    rounds exit 0, modeled-vs-measured MFU drift exits nonzero.
"""

import dataclasses
import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from tiny_deepspeed_tpu.utils.hlo_cost import (
    cost_ledger,
    cost_summary,
    hbm_bw_per_chip,
    peak_flops_per_chip,
    roofline_verdict,
    wire_bw_per_chip,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERF_DIFF = os.path.join(REPO, "scripts", "perf_diff.py")


# ---------------------------------------------------------------------------
# synthetic HLO: exact arithmetic
# ---------------------------------------------------------------------------

SYN_DOT = """
HloModule syn
ENTRY %main (p0: f32[4,5]) -> f32[4,6] {
  %p0 = f32[4,5] parameter(0)
  %w = f32[5,6] parameter(1)
  ROOT %d = f32[4,6] dot(f32[4,5] %p0, f32[5,6] %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""

SYN_BATCHED = """
HloModule syn
ENTRY %main (p0: f32[2,4,5]) -> f32[2,4,6] {
  %p0 = f32[2,4,5] parameter(0)
  %w = f32[2,5,6] parameter(1)
  ROOT %d = f32[2,4,6] dot(f32[2,4,5] %p0, f32[2,5,6] %w), lhs_batch_dims={0}, rhs_batch_dims={0}, lhs_contracting_dims={2}, rhs_contracting_dims={1}
}
"""

SYN_FUSION = """
HloModule syn
%fused_computation.1 (fp: f32[4,5]) -> f32[4,6] {
  %fp = f32[4,5] parameter(0)
  %fw = f32[5,6] constant({...})
  %big = f32[1000,1000] broadcast(%fp), dimensions={}
  ROOT %fd = f32[4,6] dot(f32[4,5] %fp, f32[5,6] %fw), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
ENTRY %main (p0: f32[4,5]) -> f32[4,6] {
  %p0 = f32[4,5] parameter(0)
  ROOT %f = f32[4,6] fusion(f32[4,5] %p0), kind=kOutput, calls=%fused_computation.1
}
"""

SYN_LOOP = """
HloModule syn
%cond (cp: (s32[], f32[4,5])) -> pred[] {
  %cp = (s32[], f32[4,5]) parameter(0)
  %iv = s32[] get-tuple-element(%cp), index=0
  %bound = s32[] constant(3)
  ROOT %lt = pred[] compare(s32[] %iv, s32[] %bound), direction=LT
}
%body (bp: (s32[], f32[4,5])) -> (s32[], f32[4,5]) {
  %bp = (s32[], f32[4,5]) parameter(0)
  %x = f32[4,5] get-tuple-element(%bp), index=1
  %w = f32[5,5] constant({...})
  %d = f32[4,5] dot(f32[4,5] %x, f32[5,5] %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %i = s32[] get-tuple-element(%bp), index=0
  %one = s32[] constant(1)
  %i2 = s32[] add(s32[] %i, s32[] %one)
  ROOT %t = (s32[], f32[4,5]) tuple(s32[] %i2, f32[4,5] %d)
}
ENTRY %main (p0: f32[4,5]) -> f32[4,5] {
  %p0 = f32[4,5] parameter(0)
  %iv0 = s32[] constant(0)
  %init = (s32[], f32[4,5]) tuple(s32[] %iv0, f32[4,5] %p0)
  %wh = (s32[], f32[4,5]) while(%init), condition=%cond, body=%body
  %out = f32[4,5] get-tuple-element(%wh), index=1
  %wt = f32[5,6] parameter(1)
  ROOT %top = f32[4,6] dot(f32[4,5] %out, f32[5,6] %wt), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""

SYN_DUS = """
HloModule syn
ENTRY %main (p0: f32[100,10]) -> f32[100,10] {
  %p0 = f32[100,10] parameter(0)
  %upd = f32[1,10] parameter(1)
  %i = s32[] parameter(2)
  ROOT %dus = f32[100,10] dynamic-update-slice(f32[100,10] %p0, f32[1,10] %upd, s32[] %i, s32[] %i)
}
"""


class TestDotFlops:
    def test_plain_dot_exact(self):
        led = cost_ledger(SYN_DOT)
        # 2 * (4*6 result) * (5 contracting) = 240
        assert led["total_flops"] == 240.0
        assert led["flops"] == {"dot": 240.0}
        assert led["count"] == {"dot": 1.0}
        assert led["flops_in_loops"] == 0.0
        (c,) = led["cost_centers"]
        assert c["flops"] == 240.0 and not c["in_loop"]
        assert "f32[4,6]" in c["sig"]

    def test_batched_dot_exact(self):
        led = cost_ledger(SYN_BATCHED)
        # 2 * (2*4*6 result) * (5 contracting) = 480 — batch dims ride
        # the result product, the contracting product excludes them
        assert led["total_flops"] == 480.0

    def test_dot_inside_fusion_payload_counted(self):
        led = cost_ledger(SYN_FUSION)
        assert led["total_flops"] == 240.0
        # HBM: the fusion LINE (operands + result = 80 + 96 bytes), not
        # the payload's internals — the f32[1000,1000] intermediate
        # (4 MB) lives in registers/VMEM and must not be charged
        assert led["hbm_bytes"] == pytest.approx(4 * (4 * 5 + 4 * 6))
        assert led["hbm_bytes"] < 1e5

    def test_trip_count_multiplies_loop_flops(self):
        led = cost_ledger(SYN_LOOP)
        # body dot: 2*(4*5)*5 = 200, x3 trips; top-level dot: 240
        assert led["flops_in_loops"] == 600.0
        assert led["total_flops"] == 840.0
        (loop,) = led["loops"]
        assert loop["trips"] == 3 and loop["resolved"]
        assert loop["flops"] == 600.0
        assert led["unresolved_loops"] == []
        # the in-loop dot's cost center is flagged loop-resident
        sigs = {c["sig"]: c for c in led["cost_centers"]}
        in_loop = [c for c in sigs.values() if c["in_loop"]]
        assert len(in_loop) == 1 and in_loop[0]["flops"] == 600.0
        assert in_loop[0]["count"] == 3.0

    def test_dynamic_update_slice_counts_slice_not_accumulator(self):
        led = cost_ledger(SYN_DUS)
        # read update (40 B) + 2 s32 indices (8 B) + write update
        # (40 B); the aliased 4000 B destination is NOT charged
        # (in-place slice update)
        assert led["hbm_bytes"] == pytest.approx(88.0)


class TestRoofline:
    def test_bound_classification(self):
        # times: compute = flops/peak, hbm = bytes/bw, wire = bytes/bw —
        # synthetic ledgers pin each verdict
        v = roofline_verdict(1e15, 1e6, 1e3, device_kind="cpu")
        assert v["bound"] == "compute"
        v = roofline_verdict(1e9, 1e12, 1e3, device_kind="cpu")
        assert v["bound"] == "hbm"
        v = roofline_verdict(1e9, 1e6, 1e12, device_kind="cpu")
        assert v["bound"] == "wire"

    def test_arithmetic_intensity_and_ridge(self):
        v = roofline_verdict(2e12, 1e9, 0.0, device_kind="v5e")
        assert v["arithmetic_intensity"] == pytest.approx(2000.0)
        assert v["ridge_intensity"] == pytest.approx(197e12 / 819e9)

    def test_device_tables(self):
        assert peak_flops_per_chip("TPU v5e") == 197e12
        assert peak_flops_per_chip("TPU v5p") == 459e12
        assert peak_flops_per_chip(None) == 197e12
        assert hbm_bw_per_chip("TPU v4") == 1228e9
        assert wire_bw_per_chip("TPU v6 lite") == 448e9

    def test_cost_summary_shape(self):
        led = cost_ledger(SYN_LOOP)
        s = cost_summary(led, device_kind="cpu", wire_bytes=123.0)
        assert s["bound"] in ("compute", "hbm", "wire")
        assert s["total_flops"] == 840.0
        assert s["wire_bytes"] == 123.0
        assert len(s["top_cost_centers"]) <= 3
        assert s["top_cost_centers"][0]["share"] <= 1.0
        json.dumps(s)  # JSON-safe by construction

    def test_compute_span_template(self):
        from tiny_deepspeed_tpu.telemetry.trace import (
            compute_span_template,
        )
        led = cost_ledger(SYN_LOOP)
        spans = compute_span_template(
            [lo for lo in led["loops"] if lo["flops"] > 0],
            float(led["total_flops"]),
        )
        # 3 per-trip spans (trips=3 <= 64) + 1 top-level
        loop_spans = [s for s in spans if s["loop_resident"]]
        top = [s for s in spans if not s["loop_resident"]]
        assert len(loop_spans) == 3 and len(top) == 1
        assert sum(s["flops"] for s in spans) == pytest.approx(840.0)
        assert top[0]["flops"] == pytest.approx(240.0)
        assert all(s["schematic"] for s in spans)


# ---------------------------------------------------------------------------
# compiled-program pins (abstract state: eval_shape, no real buffers)
# ---------------------------------------------------------------------------

def _compiled_text(model_name: str, b=1, t=1024):
    from tiny_deepspeed_tpu import AdamW, SingleDevice
    from tiny_deepspeed_tpu.models import ALL_PRESETS
    from tiny_deepspeed_tpu.models.gpt2 import GPT2Model
    from tiny_deepspeed_tpu.models.moe import MoEConfig, MoEGPT

    cfg = dataclasses.replace(ALL_PRESETS[model_name], remat=False)
    model = MoEGPT(cfg) if isinstance(cfg, MoEConfig) else GPT2Model(cfg)
    eng = SingleDevice(model, AdamW(lr=1e-3))
    abstate = jax.eval_shape(eng.init, jax.random.PRNGKey(0))
    idx = jax.ShapeDtypeStruct((b, t), jnp.int32)
    text = eng._step.lower(abstate, (idx, idx)).compile().as_text()
    return cfg, model, text


class TestPinned124M:
    def test_hlo_counted_within_2pct_of_bench_formula(self):
        """The acceptance pin: bench's analytic `flops_tok_matmul` for
        the 124M GPT-2 train step (b=1, t=1024, remat off) agrees with
        the FLOPs counted from the compiled program within 2%."""
        b, t = 1, 1024
        cfg, model, text = _compiled_text("gpt2-124m", b=b, t=t)
        led = cost_ledger(text)
        n_params = model.num_params()
        embed = cfg.vocab_size * cfg.n_embd + cfg.block_size * cfg.n_embd
        analytic_tok = (6 * (n_params - embed)
                        + 12 * cfg.n_layer * t * cfg.n_embd)
        analytic_step = analytic_tok * b * t
        assert led["total_flops"] == pytest.approx(analytic_step,
                                                   rel=0.02)
        # per-layer attribution rides the scan: a 12-trip loop carries
        # the layer compute (in-loop trip multiplication vs scan length)
        scan_loops = [lo for lo in led["loops"]
                      if lo["trips"] == cfg.n_layer and lo["flops"] > 0]
        assert scan_loops, led["loops"]
        assert led["flops_in_loops"] > 0.5 * led["total_flops"]
        assert led["unresolved_loops"] == []


class TestPinnedMoE:
    def test_dispatch_undercount_demonstrated_then_corrected(self):
        """models/moe.py:52's admission, quantified: the old analytic
        formula (active expert params only) undercounts the compiled
        moe-8x124m step by the dispatch/combine einsum FLOPs; adding
        `dispatch_combine_flops_per_token` closes it to within 2%."""
        from tiny_deepspeed_tpu.models.moe import (
            dispatch_combine_flops_per_token,
        )

        b, t = 1, 1024
        cfg, model, text = _compiled_text("moe-8x124m", b=b, t=t)
        led = cost_ledger(text)
        n_params = model.num_params()
        embed = cfg.vocab_size * cfg.n_embd + cfg.block_size * cfg.n_embd
        expert = sum(
            int(math.prod(s.shape))
            for n, s in model.param_shapes().items()
            if ".moe." in n and "router" not in n
        )
        # the OLD bench accounting: expert params scaled k/E, einsum
        # pair ignored entirely
        old_active = (n_params - expert
                      + expert * cfg.expert_top_k // cfg.n_expert)
        old_tok = (6 * (old_active - embed)
                   + 12 * cfg.n_layer * t * cfg.n_embd)
        # the CORRECTED accounting (bench run_one, in lock-step):
        # capacity-padded expert compute (E*C slot-rows, not k/E) + the
        # dispatch/combine einsum matmuls
        cap = max(1, int(cfg.capacity_factor * cfg.expert_top_k * b * t
                         / cfg.n_expert))
        new_active = n_params - expert + expert * cap // (b * t)
        fix_tok = (6 * (new_active - embed)
                   + 12 * cfg.n_layer * t * cfg.n_embd
                   + dispatch_combine_flops_per_token(cfg, b * t))
        counted = led["total_flops"]
        # demonstrated: the compiled program does >10% more matmul work
        # than the old formula claims (uncounted einsums + the
        # capacity padding)
        assert counted > 1.10 * old_tok * b * t, (
            counted, old_tok * b * t)
        # corrected: the new formula agrees with the counted number
        assert counted == pytest.approx(fix_tok * b * t, rel=0.02)


# ---------------------------------------------------------------------------
# perf_diff sentinel (real CLI: the exit codes ARE the contract)
# ---------------------------------------------------------------------------

def _round(tmp_path, name, value, mm=None, mh=None,
           cached=False, metric="gpt2-124m_train_tokens_per_sec_per_chip"):
    extra = {"chips": 1, "seq_len": 1024}
    if mm is not None:
        extra["matmul_mfu"] = mm
    if mh is not None:
        extra["hlo_cost"] = {"mfu_hlo": mh, "total_flops": 1e12}
    if cached:
        extra["cached_result"] = True
    p = tmp_path / name
    p.write_text(json.dumps({
        "n": 1, "cmd": "bench", "rc": 0, "tail": "",
        "parsed": {"metric": metric, "value": value,
                   "unit": "tokens/s/chip", "extra": extra},
    }))
    return str(p)


def _run(*args):
    return subprocess.run(
        [sys.executable, PERF_DIFF, *args],
        capture_output=True, text=True, timeout=60,
    )


class TestPerfDiff:
    def test_injected_regression_exits_nonzero_naming_fingerprint(
            self, tmp_path):
        r1 = _round(tmp_path, "BENCH_r01.json", 100000.0)
        r2 = _round(tmp_path, "BENCH_r02.json", 90000.0)  # -10%
        r = _run("--check", r1, r2)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "REGRESSION" in r.stdout
        assert "gpt2-124m_train_tokens_per_sec_per_chip" in r.stdout
        assert "chips=1" in r.stdout and "seq_len=1024" in r.stdout

    def test_identical_rounds_exit_zero(self, tmp_path):
        r1 = _round(tmp_path, "BENCH_r01.json", 100000.0)
        r2 = _round(tmp_path, "BENCH_r02.json", 100000.0)
        r = _run("--check", r1, r2)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "OK" in r.stdout

    def test_delta_inside_noise_spread_not_flagged(self, tmp_path):
        # prior rounds spread 10% -> an 8% drop proves nothing
        r1 = _round(tmp_path, "BENCH_r01.json", 90000.0)
        r2 = _round(tmp_path, "BENCH_r02.json", 100000.0)
        r3 = _round(tmp_path, "BENCH_r03.json", 92000.0)
        r = _run("--check", r1, r2, r3)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_mfu_drift_flagged(self, tmp_path):
        r1 = _round(tmp_path, "BENCH_r01.json", 100000.0,
                    mm=0.50, mh=0.30)
        r = _run("--check", r1)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "DRIFT" in r.stdout and "matmul_mfu" in r.stdout

    def test_mfu_agreement_not_flagged(self, tmp_path):
        r1 = _round(tmp_path, "BENCH_r01.json", 100000.0,
                    mm=0.31, mh=0.30)
        r = _run("--check", r1)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_cached_replays_are_not_fresh(self, tmp_path):
        # BENCH_r04/r05 shape: same value replayed from the last-good
        # cache — must not be diffed (and must not mask a later drop)
        r1 = _round(tmp_path, "BENCH_r01.json", 127603.2, cached=True)
        r2 = _round(tmp_path, "BENCH_r02.json", 127603.2, cached=True)
        r = _run("--check", r1, r2)
        assert r.returncode == 0
        assert "0 fresh" in r.stdout

    def test_committed_trajectory_is_green(self):
        rounds = sorted(
            os.path.join(REPO, f) for f in os.listdir(REPO)
            if f.startswith("BENCH_r") and f.endswith(".json")
        )
        if not rounds:
            pytest.skip("no committed BENCH_*.json rounds")
        r = _run("--check", *rounds)
        assert r.returncode == 0, r.stdout + r.stderr
