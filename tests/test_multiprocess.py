# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""REAL multi-process execution: 2 OS processes x 2 virtual CPU devices,
stitched by jax.distributed into one 4-device backend (round-2 verdict
item: init_distributed and the hybrid mesh had only ever been exercised by
mocks; the reference at least runs under torchrun --nproc_per_node N,
/root/reference/README.md:39-45).

Each worker (tests/mp_worker.py) calls init_distributed with the explicit
coordinator kwargs (the torchrun-rendezvous equivalent), builds the mesh
over the 4 GLOBAL devices, feeds its addressable shard of a global batch,
and runs two DDP steps — the gradient all-reduce crosses the process
boundary for real.  The parent asserts both workers compute IDENTICAL
losses, and that they match a single-process 4-device run of the same
model + batch.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute composition suite (see pytest.ini)

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("engine", ["DDP", "Zero3"])
def test_two_process_step(engine):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "mp_worker.py"),
             str(i), "2", str(port), engine],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process workers timed out (coordinator hang?)")

    for rc, out, err in outs:
        if rc != 0 and ("UNIMPLEMENTED" in err or "not supported" in err
                        or "NotImplementedError" in err):
            pytest.skip(f"multi-process CPU collectives unsupported: "
                        f"{err[-200:]}")
        assert rc == 0, f"worker failed rc={rc}:\n{err[-2000:]}"

    recs = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in outs]
    assert {r["process"] for r in recs} == {0, 1}
    assert all(r["devices"] == 4 for r in recs)
    # both processes see the same replicated loss
    np.testing.assert_allclose(recs[0]["losses"], recs[1]["losses"],
                               rtol=1e-6)

    # and the distributed run matches a single-process run bit-for-bit in
    # trajectory shape: same model, same global batch, 4 local devices
    code = (
        "import os, json, numpy as np;"
        "import sys; sys.path.insert(0, %r);"
        "import jax;"
        "jax.config.update('jax_platforms', 'cpu');"
        "jax.config.update('jax_num_cpu_devices', 4);"
        "import jax.numpy as jnp;"
        "from tiny_deepspeed_tpu import AdamW, GPT2Model, GPTConfig;"
        "from tiny_deepspeed_tpu.parallel.mesh import make_mesh;"
        "cfg = GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,"
        "                n_embd=16, compute_dtype=jnp.float32);"
        "import tiny_deepspeed_tpu as tds;"
        "eng = getattr(tds, %r)(GPT2Model(cfg), AdamW(lr=1e-3),"
        "                       mesh=make_mesh());"
        "state = eng.init(jax.random.PRNGKey(0));"
        "rng = np.random.default_rng(0);"
        "idx = jnp.asarray(rng.integers(0, 64, (8, 16), dtype=np.int32));"
        "tgt = jnp.asarray(rng.integers(0, 64, (8, 16), dtype=np.int32));"
        "losses = [];\n"
        "for _ in range(2):\n"
        "    state, loss = eng.step(state, (idx, tgt))\n"
        "    losses.append(float(loss))\n"
        "print(json.dumps(losses))"
    ) % (os.path.dirname(HERE), engine)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    ref = json.loads(r.stdout.strip().splitlines()[-1])
    np.testing.assert_allclose(recs[0]["losses"], ref, rtol=1e-5)
