# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""ZeRO++-style fp8 weight gather (GPTConfig.gather_quant="fp8").

The block matmul weights stack as float8_e4m3 + per-output-channel scales so
the ZeRO-3 per-layer gather moves 1-byte values (qwZ, arxiv 2306.10209 —
fp8 rather than int8 so the cast stays differentiable).  These tests pin the
semantics: near-full-precision forward, convergent training under ZeRO-3,
f8 present in the compiled step, and family coverage."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute composition suite (see pytest.ini)

from tiny_deepspeed_tpu import (
    AdamW, GPTConfig, GPT2Model, LlamaConfig, LlamaModel, MoEConfig, MoEGPT,
    SingleDevice, Zero3,
)

CFG = dict(block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
           compute_dtype=jnp.float32)


def _batch(b=8):
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    return (jax.random.randint(k1, (b, 32), 0, 128),
            jax.random.randint(k2, (b, 32), 0, 128))


class TestFp8Gather:
    def test_forward_close_to_full_precision(self):
        mq = GPT2Model(GPTConfig(gather_quant="fp8", **CFG))
        mf = GPT2Model(GPTConfig(**CFG))
        p = mf.init(jax.random.PRNGKey(0))
        idx, tgt = _batch()
        lf, lq = float(mf.apply(p, idx, tgt)), float(mq.apply(p, idx, tgt))
        assert abs(lf - lq) / lf < 5e-3

    def test_stacked_tree_is_fp8(self):
        m = GPT2Model(GPTConfig(gather_quant="fp8", **CFG))
        p = m.init(jax.random.PRNGKey(0))
        st = m.stacked_compute_params(p)
        for name in ("attn.qkv.w", "attn.proj.w", "mlp.fc.w", "mlp.proj.w"):
            assert st[name].dtype == jnp.float8_e4m3fn
            assert st[name + "#scale"].dtype == jnp.float32
        # norms/biases untouched
        assert st["ln_1.w"].dtype == jnp.float32
        # roundtrip error bounded by e4m3 resolution (~2^-3 relative)
        w = np.asarray(p["h.attn.qkv.w"], np.float64)
        deq = (np.asarray(st["attn.qkv.w"], np.float64)
               * np.asarray(st["attn.qkv.w#scale"], np.float64))
        denom = np.maximum(np.abs(w), 1e-6)
        assert float(np.max(np.abs(deq - w) / denom)) < 0.13

    def test_zero3_trains_and_gathers_sub_f32(self):
        m = GPT2Model(GPTConfig(gather_quant="fp8", **CFG))
        eng = Zero3(m, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        batch = _batch()
        losses = []
        for _ in range(4):
            state, loss = eng.step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        txt = eng._step.lower(state, batch).compile().as_text()
        assert "f8e4m3" in txt  # quantized values reach the compiled step
        # the property the _bw constraint buys on this backend: the FORWARD
        # per-layer weight gathers move sub-f32 values (XLA CPU upcasts f8
        # to f16 for the collective — 2 bytes, half of this f32-compute
        # config's full precision; the four f16 gathers below are the four
        # block weights).  Backward/remat paths still emit some f32 gathers
        # — GSPMD's call, documented in the config knob.  A regression
        # dropping the constraint dequantizes shard-side and gathers ONLY
        # f32, which this catches.
        import re
        sub_f32 = [
            ln for ln in txt.splitlines()
            if re.search(r"%all-gather[.\d]* = f(8\w*|16)\[\d+,\d+\]", ln)
        ]
        assert len(sub_f32) >= 4, (
            f"expected >=4 sub-f32 2-D weight all-gathers, got "
            f"{len(sub_f32)}"
        )

    def test_loss_curve_tracks_unquantized(self):
        """Round-2 advice: the dW cotangent crosses the quantization edge in
        e4m3 (scaled by the forward per-channel absmax) — a real gradient-
        precision loss.  A strict straight-through estimator can't keep the
        backward in compute dtype without also gathering full-precision
        weights (the cotangent must dtype-match the f8 leaf), so instead
        this validates the consequence directly: a 30-step loss curve under
        fp8 gather stays within a few percent of the unquantized path."""
        def run(quant):
            m = GPT2Model(GPTConfig(
                gather_quant="fp8" if quant else None, **CFG))
            eng = SingleDevice(m, AdamW(lr=1e-3))
            state = eng.init(jax.random.PRNGKey(0))
            batch = _batch()
            losses = []
            for _ in range(30):
                state, loss = eng.step(state, batch)
                losses.append(float(loss))
            return losses
        base, quant = run(False), run(True)
        # same init, same data: trajectories must track closely the whole way
        rel = [abs(a - b) / a for a, b in zip(base, quant)]
        assert max(rel) < 0.05, f"max divergence {max(rel):.3f}"
        assert quant[-1] < quant[0] - 0.3  # and it does actually train

    @pytest.mark.parametrize("family", ["llama", "moe"])
    def test_other_families(self, family):
        if family == "llama":
            m = LlamaModel(LlamaConfig(gather_quant="fp8", **CFG))
        else:
            m = MoEGPT(MoEConfig(gather_quant="fp8", n_expert=2, **CFG))
        p = m.init(jax.random.PRNGKey(0))
        if family == "moe":
            # router excluded from quantization (softmax/top-k stability)
            assert m.stacked_compute_params(p)["moe.router.w"].dtype \
                == jnp.float32
        eng = SingleDevice(m, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        batch = _batch()
        losses = []
        for _ in range(3):
            state, loss = eng.step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_generate_works_quantized(self):
        m = GPT2Model(GPTConfig(gather_quant="fp8", **CFG))
        p = m.init(jax.random.PRNGKey(0))
        idx = jnp.array([[1, 2, 3]], jnp.int32)
        a = m.generate(p, idx, 5, temperature=0.0, use_cache=True)
        b = m.generate(p, idx, 5, temperature=0.0, use_cache=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
