# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Worker for tests/test_serving.py's kill-mid-trace recovery test —
NOT a pytest module.

Run as:  python serving_worker.py <mode> <journal_path>

Modes:
  serve    — submit the fixed 4-request trace through an engine with a
             request journal; at the Nth scheduler tick, SIGKILL
             ourselves from the journal's commit hook — i.e. a REAL
             process death between journal-append and fsync, the worst
             write moment (no cleanup, no excepthook).
  recover  — build a FRESH engine on the same journal,
             `ServingEngine.recover()`, drain, print one JSON line
             {"recovered": [ids], "outputs": {id: [tokens]}}.
  straight — the same 4 submissions through a journal-less engine,
             uninterrupted; print {"outputs": {id: [tokens]}}.

The parent asserts: the kill left in-flight requests in the journal;
recovery re-queues them front-of-line with their committed prefix; and
every recovered request's FINAL token sequence equals the straight
run's (greedy — the (seed, position) sampling keys make it exact).
"""

import json
import os
import sys

mode, journal_path = sys.argv[1], sys.argv[2]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TINY_DS_NO_COMPILE_CACHE", "1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tiny_deepspeed_tpu import GPT2Model, GPTConfig  # noqa: E402
from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine  # noqa: E402

CFG = GPTConfig(block_size=64, vocab_size=128, n_layer=2, n_head=2,
                n_embd=32, compute_dtype=jnp.float32)
SCFG = ServeConfig(max_active=2, num_blocks=24, block_tokens=8)
# (prompt seed, prompt len, max_new): 2 admit immediately, 2 queue —
# the kill at tick 5 lands with requests in EVERY lifecycle state
SPECS = [(1, 7, 12), (2, 13, 12), (3, 7, 12), (4, 13, 12)]
KILL_AT_TICK = 5


def _prompt(seed, n):
    return np.asarray(
        jax.random.randint(jax.random.PRNGKey(seed), (n,), 0, 128),
        np.int32,
    ).tolist()


model = GPT2Model(CFG)
params = model.init(jax.random.PRNGKey(0))

if mode == "straight":
    eng = ServingEngine(model, params, SCFG)
    reqs = [eng.submit(_prompt(s, n), new) for s, n, new in SPECS]
    eng.drain(max_ticks=500)
    print(json.dumps({"outputs": {r.id: r.tokens for r in reqs}}),
          flush=True)
elif mode == "serve":
    eng = ServingEngine(model, params, SCFG, journal=journal_path)
    for s, n, new in SPECS:
        eng.submit(_prompt(s, n), new)
    for t in range(500):
        if t == KILL_AT_TICK:
            # a REAL kill between the tick's journal append and its
            # fsync commit: the journal hook fires inside commit()
            eng.journal.arm_commit_hook(
                lambda: os.kill(os.getpid(), 9))
        eng.tick()
    raise SystemExit("worker was supposed to be SIGKILLed")  # pragma: no cover
elif mode == "recover":
    eng = ServingEngine(model, params, SCFG, journal=journal_path)
    rec = eng.recover()
    eng.drain(max_ticks=500)
    print(json.dumps({
        "recovered": [r.id for r in rec],
        "outputs": {r.id: r.tokens for r in rec},
        "statuses": {r.id: r.status for r in rec},
    }), flush=True)
else:  # pragma: no cover
    raise SystemExit(f"unknown mode {mode!r}")
