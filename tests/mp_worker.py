# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Worker for tests/test_multiprocess.py — NOT a pytest module.

Run as:  python mp_worker.py <process_id> <num_processes> <port> [engine]

Each process owns 2 virtual CPU devices; jax.distributed.initialize stitches
them into one 4-device global backend, exercising the REAL multi-process
path through parallel/mesh.py (round-2 verdict: granule logic had only ever
run against mocked device attrs — no two-process run existed anywhere).
Prints one JSON line the parent asserts on.
"""

import json
import os
import sys

proc_id, n_proc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
engine_name = sys.argv[4] if len(sys.argv) > 4 else "DDP"
os.environ.pop("JAX_COORDINATOR_ADDRESS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# this image's sitecustomize imports jax at interpreter start, so env vars
# are captured too early — config updates are authoritative (see conftest)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
# cross-PROCESS collectives on the CPU backend need a real transport
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from tiny_deepspeed_tpu.parallel.mesh import init_distributed, make_mesh  # noqa: E402

# the EXPLICIT-kwargs path of init_distributed (the torchrun-rendezvous
# equivalent; auto-config only exists on Cloud TPU pods)
init_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=n_proc,
    process_id=proc_id,
)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

assert jax.process_count() == n_proc, jax.process_count()
assert len(jax.local_devices()) == 2
assert len(jax.devices()) == 2 * n_proc

import tiny_deepspeed_tpu as tds  # noqa: E402
from tiny_deepspeed_tpu import AdamW, GPT2Model, GPTConfig  # noqa: E402

mesh = make_mesh()  # all 4 global devices on one "data" axis
# 2 processes x 2 local devices: _n_granules sees distinct process_index
# values, so make_mesh takes the HYBRID layout path for real (the round-2
# gap: granule logic was only ever exercised against mocked device attrs).
# The hybrid grid keeps each process's devices contiguous on the data axis.
_grid = mesh.devices.ravel()
_procs = [d.process_index for d in _grid]
assert sorted(_procs) == [0, 0, 1, 1], _procs
assert _procs[0] == _procs[1] and _procs[2] == _procs[3], _procs
cfg = GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                n_embd=16, compute_dtype=jnp.float32)
model = GPT2Model(cfg)
# DDP: the grad all-reduce crosses the process boundary.  Zero3: params
# LIVE sharded across the two processes and every per-layer all-gather is
# a cross-process collective.
eng = getattr(tds, engine_name)(model, AdamW(lr=1e-3), mesh=mesh)
state = eng.init(jax.random.PRNGKey(0))

# global batch (B=8, T=16): same numpy stream on every process, each feeds
# ONLY its addressable shard via make_array_from_process_local_data
rng = np.random.default_rng(0)
idx_g = rng.integers(0, 64, (8, 16), dtype=np.int32)
tgt_g = rng.integers(0, 64, (8, 16), dtype=np.int32)
sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
idx = jax.make_array_from_process_local_data(
    sharding, idx_g[proc_id * 4:(proc_id + 1) * 4], idx_g.shape
)
tgt = jax.make_array_from_process_local_data(
    sharding, tgt_g[proc_id * 4:(proc_id + 1) * 4], tgt_g.shape
)

losses = []
for _ in range(2):
    state, loss = eng.step(state, (idx, tgt))
    losses.append(float(loss))

print(json.dumps({"process": proc_id, "losses": losses,
                  "engine": engine_name,
                  "devices": len(jax.devices())}), flush=True)
jax.distributed.shutdown()
