# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Telemetry subsystem on the CPU mesh: on-device health metrics vs host
recomputation across ZeRO stages, telemetry-off HLO identity (the knob is
free when off), step-timer upgrades (p50/p95, segments, recompile
attribution, exception safety), anomaly one-shot firing, the JSONL schema
round-trip through scripts/report_run.py, and the bench telemetry sidecar.
"""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import (
    AdamW, DDP, GPTConfig, GPT2Model, SingleDevice, Telemetry, Zero2, Zero3,
)
from tiny_deepspeed_tpu.telemetry import HEALTH_FIELDS, health_dict, schema
from tiny_deepspeed_tpu.utils import MetricsLogger, StepTimer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)


def make_batch(seed=1, b=8, t=32, vocab=128):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return (jax.random.randint(k1, (b, t), 0, vocab),
            jax.random.randint(k2, (b, t), 0, vocab))


@pytest.fixture(scope="module")
def model():
    return GPT2Model(TINY)


@pytest.fixture(scope="module")
def ddp_off(model):
    return DDP(model, AdamW(lr=1e-3))


@pytest.fixture(scope="module")
def ddp_on(model):
    telem = Telemetry()
    return DDP(model, AdamW(lr=1e-3), telemetry=telem), telem


def _tree_sq_sum(tree):
    return sum(
        float(np.sum(np.square(np.asarray(x, dtype=np.float64))))
        for x in jax.tree.leaves(tree)
    )


class TestHealthMetrics:
    """Health-vector values match an independent host-side recompute for a
    tiny GPT-2, across ZeRO stages 0/2/3 (the norms are GLOBAL: XLA psums
    the sharded partial sums, so every stage must report the same
    numbers)."""

    # tier-1 budget (scripts/tier1_times.py): DDP's replicated grads are
    # the degenerate case of the cross-shard psum the Zero2/Zero3 rows
    # pin — it runs in the full tier
    @pytest.mark.parametrize("eng_cls", [
        pytest.param(DDP, marks=pytest.mark.slow), Zero2, Zero3,
    ])
    def test_matches_host_recompute(self, model, eng_cls):
        telem = Telemetry()
        eng = eng_cls(model, AdamW(lr=1e-3), telemetry=telem)
        state = eng.init(jax.random.PRNGKey(0))
        idx, tgt = make_batch(7)

        # host-side copies BEFORE the step (the step donates its input)
        before = {
            n: np.asarray(p, dtype=np.float64)
            for n, p in state.params.items()
        }
        # independent grad recompute: plain autodiff of the model's loss on
        # replicated params (single-device pctx)
        sd = SingleDevice(model, AdamW(lr=1e-3))
        ref_params = {n: jnp.asarray(v, jnp.float32) for n, v in
                      before.items()}
        loss_ref, grads_ref = jax.value_and_grad(
            lambda p: model.apply(p, idx, tgt, pctx=sd.pctx)
        )(ref_params)

        state, loss = eng.step(state, (idx, tgt))
        h = telem.poll()
        assert h is not None and set(h) == set(HEALTH_FIELDS)

        assert h["nonfinite_grads"] == 0
        np.testing.assert_allclose(h["loss"], float(loss_ref), rtol=1e-4)
        np.testing.assert_allclose(
            h["grad_norm"], np.sqrt(_tree_sq_sum(grads_ref)), rtol=2e-3,
        )
        after = {
            n: np.asarray(p, dtype=np.float64)
            for n, p in state.params.items()
        }
        np.testing.assert_allclose(
            h["param_norm"], np.sqrt(_tree_sq_sum(after)), rtol=2e-3,
        )
        upd_sq = sum(
            float(np.sum(np.square(after[n] - before[n]))) for n in after
        )
        np.testing.assert_allclose(
            h["update_norm"], np.sqrt(upd_sq), rtol=5e-3,
        )

    def test_health_dict_field_order(self):
        vec = np.array([1.5, 2.0, 3.0, 4.0, 0.0])
        h = health_dict(vec)
        assert h["loss"] == 1.5  # loss MUST be element 0 (the sync barrier)
        assert h["nonfinite_grads"] == 0
        assert isinstance(h["nonfinite_grads"], int)


class TestTelemetryOffIsFree:
    """Acceptance: telemetry is opt-in and free when off."""

    def test_off_program_identical_to_default(self, model, ddp_off):
        """telemetry=None lowers the byte-identical step program as an
        engine constructed without the knob at all."""
        eng_none = DDP(model, AdamW(lr=1e-3), telemetry=None)
        state = ddp_off.init(jax.random.PRNGKey(0))
        batch = make_batch(1)
        text_default = ddp_off._step.lower(state, batch).as_text()
        state2 = eng_none.init(jax.random.PRNGKey(0))
        text_none = eng_none._step.lower(state2, batch).as_text()
        assert text_default == text_none

    @pytest.mark.slow  # tier-1 budget: telemetry-off byte-identity is
    # the quick primary pin; this ledger corollary runs in the full tier
    def test_off_vs_on_collective_ledger(self, model, ddp_off, ddp_on):
        """The health norms may add only scalar-sized reductions: the
        telemetry-on step's collective ledger stays within 1 KB of the
        off step's."""
        from tiny_deepspeed_tpu.utils.hlo_comm import hlo_comm_report
        batch = make_batch(1)
        eng_on, _ = ddp_on
        led_off = hlo_comm_report(
            ddp_off, ddp_off.init(jax.random.PRNGKey(0)), batch
        )
        led_on = hlo_comm_report(
            eng_on, eng_on.init(jax.random.PRNGKey(0)), batch
        )
        assert abs(led_on["total_wire_bytes"]
                   - led_off["total_wire_bytes"]) <= 1024

    @pytest.mark.slow  # tier-1 budget: subsumed by the byte-identity
    # pin (identical programs have identical signatures) — full tier
    def test_step_returns_same_signature(self, model, ddp_off, ddp_on):
        eng_on, telem = ddp_on
        batch = make_batch(1)
        s_off, l_off = ddp_off.step(
            ddp_off.init(jax.random.PRNGKey(0)), batch
        )
        s_on, l_on = eng_on.step(eng_on.init(jax.random.PRNGKey(0)), batch)
        assert float(l_off) == float(l_on)
        assert telem.poll()["loss"] == float(l_on)

    def test_overhead_under_two_percent(self, model, ddp_off, ddp_on):
        """<2% step-time overhead on the CPU-mesh tiny config, measured by
        StepTimer p50.  XLA-CPU step times drift +-40% with machine load,
        so the two engines are sampled INTERLEAVED (drift hits both
        distributions equally) with a small absolute guard for timer
        granularity on top of the 2% relative bound."""
        eng_on, _ = ddp_on
        batch = make_batch(1)
        timers = {False: StepTimer(), True: StepTimer()}
        states = {False: ddp_off.init(jax.random.PRNGKey(0)),
                  True: eng_on.init(jax.random.PRNGKey(0))}
        engines = {False: ddp_off, True: eng_on}
        for eng, state in engines.items():  # warm both compiles
            states[eng], _ = engines[eng].step(states[eng], batch)
        for _ in range(16):
            for on in (False, True):
                timer = timers[on]
                with timer.step() as t:
                    states[on], loss = engines[on].step(states[on], batch)
                    t.observe(loss)
        # compare best-case samples: scheduler noise on the 8-thread CPU
        # mesh is one-sided (a step is only ever SLOWED by load), so the
        # minimum over interleaved samples is the stable estimate of each
        # program's true cost; a small absolute guard covers CPU fusion-
        # dispatch granularity that a real accelerator doesn't see
        off = min(timers[False].times)
        on = min(timers[True].times)
        assert on <= off * 1.02 + 0.003, (on, off)


class TestStepTimerUpgrades:
    def test_percentiles(self):
        timer = StepTimer()
        timer.times = [10.0] + [0.1] * 10 + [0.2]  # first sample dropped
        assert timer.p50_s == pytest.approx(0.1)
        assert timer.p95_s <= 0.2
        assert timer.p95_s >= 0.1

    def test_failed_step_clears_observed_output(self):
        timer = StepTimer()
        with pytest.raises(RuntimeError):
            with timer.step() as t:
                t.observe(jnp.ones((4,)))
                raise RuntimeError("boom")
        assert timer._last_out is None
        assert timer.times == []  # no sample recorded for the failed step
        # and the next step does not sync the stale output
        with timer.step() as t:
            pass
        assert len(timer.times) == 1

    def test_marks_split_segments(self):
        timer = StepTimer()
        with timer.step() as t:
            t.mark("data")
            t.mark("h2d")
        seg = timer.segments[-1]
        assert set(seg) == {"data_s", "h2d_s", "compute_s"}
        assert abs(sum(seg.values()) - timer.times[-1]) < 0.05

    def test_compile_watch_counts_lowerings(self):
        f = jax.jit(lambda x: x * 2)
        timer = StepTimer()
        timer.watch(f)
        with timer.step() as t:
            t.observe(f(jnp.ones((4,))))
        with timer.step() as t:
            t.observe(f(jnp.ones((4,))))
        with timer.step() as t:  # new shape -> recompile
            t.observe(f(jnp.ones((8,))))
        assert timer.compiled_steps == [1, 0, 1]
        assert timer.compile_count == 2

    def test_fetch_full_delivers_whole_vector(self):
        timer = StepTimer(fetch_full=True)
        with timer.step() as t:
            t.observe(jnp.arange(5.0))
        assert timer.last_value == 0.0
        np.testing.assert_array_equal(timer.last_host,
                                      np.arange(5.0, dtype=np.float32))


class TestMetricsLoggerContextManager:
    def test_closes_on_exception(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with pytest.raises(ValueError):
            with MetricsLogger(path, stdout=False) as ml:
                ml.log(0, loss=1.0)
                fh = ml._fh
                raise ValueError("boom")
        assert ml._fh is None and fh.closed
        # close() still works standalone (and is idempotent)
        ml2 = MetricsLogger(path, stdout=False)
        ml2.close()
        ml2.close()

    def test_log_meta_writes_kind_record(self, tmp_path, capsys):
        path = str(tmp_path / "m.jsonl")
        with MetricsLogger(path, stdout=True) as ml:
            ml.log_meta(kind="run_meta", engine="DDP(...)", devices=8)
        assert capsys.readouterr().out == ""  # meta is JSONL-only
        rec = json.loads(open(path).read().strip())
        assert rec["kind"] == "run_meta" and rec["devices"] == 8


class TestAnomalyTrigger:
    def _telem(self, tmp_path, calls):
        return Telemetry(
            trace_dir=str(tmp_path),
            anomaly_factor=2.0,
            anomaly_min_steps=3,
            tracer=(lambda p: calls.append(("start", p)),
                    lambda: calls.append(("stop",))),
        )

    def test_fires_exactly_once(self, tmp_path):
        calls = []
        telem = self._telem(tmp_path, calls)
        for _ in range(5):
            assert not telem.note_step_time(0.1)
        assert telem.note_step_time(0.5)         # injected slow step
        assert not telem.note_step_time(0.5)     # armed: no re-fire
        # the NEXT instrumented step runs under the tracer, once
        for _ in range(3):
            with telem.step() as t:
                t.observe(jnp.float32(1.0))
        assert calls == [("start", os.path.join(str(tmp_path), "anomaly")),
                         ("stop",)]
        assert telem.counters["anomaly_traces"].value == 1
        assert telem.counters["anomalies"].value == 1
        # later slow steps never re-arm
        assert not telem.note_step_time(10.0)

    def test_no_trace_dir_still_fires_once(self, tmp_path):
        telem = Telemetry(anomaly_factor=2.0, anomaly_min_steps=3,
                          tracer=(lambda p: None, lambda: None))
        for _ in range(4):
            telem.note_step_time(0.1)
        assert telem.note_step_time(1.0)
        assert not telem.note_step_time(1.0)
        assert telem.counters["anomalies"].value == 1


def _load_report_run():
    spec = importlib.util.spec_from_file_location(
        "report_run_under_test", os.path.join(REPO, "scripts",
                                              "report_run.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def run_jsonl(tmp_path_factory, ddp_on):
    """A real instrumented mini-run's JSONL: run_meta (measured HLO
    ledger), per-step records with health + segments, and the final
    telemetry_summary."""
    eng, telem = ddp_on
    path = str(tmp_path_factory.mktemp("telem") / "run.jsonl")
    state = eng.init(jax.random.PRNGKey(0))
    batch = make_batch(3)
    with MetricsLogger(path, stdout=False) as ml:
        ml.log_meta(**telem.run_meta(
            state, batch, model="tiny", n_params=eng.model.num_params(),
            batch=8, seq_len=32, tokens_per_step=8 * 32,
        ))
        for i in range(3):
            with telem.step() as t:
                t.mark("data")
                t.mark("h2d")
                state, loss = eng.step(state, batch)
            ml.log(i, loss=telem.last_health["loss"],
                   step_s=telem.timer.times[-1],
                   tokens_per_s=8 * 32 / max(telem.timer.times[-1], 1e-9),
                   **telem.step_record())
        telem.flush(ml)
    return path


class TestSchemaAndReport:
    def test_schema_validates_clean_run(self, run_jsonl):
        counts, errs = schema.validate_file(run_jsonl)
        assert errs == []
        assert counts["step"] == 3 and counts["meta"] == 2

    def test_schema_rejects_drift(self):
        assert schema.validate_record({"step": 0}) != []          # no ts
        assert schema.validate_record(
            {"step": 0, "ts": 1.0, "mystery_field": 1}
        ) != []
        assert schema.validate_record(
            {"step": 0, "ts": 1.0, "loss": "high"}
        ) != []
        assert schema.validate_record(
            {"kind": "nope", "ts": 1.0}
        ) != []
        assert schema.validate_record(
            {"step": 0, "ts": 1.0, "loss": 2.5, "grad_norm": 0.1}
        ) == []

    def test_report_renders_markdown(self, run_jsonl):
        rr = _load_report_run()
        metas, steps, errs = rr.load_run(run_jsonl)
        assert errs == []
        report = rr.render_report(metas, steps, source=run_jsonl)
        assert "# Run report" in report
        assert "## Throughput" in report
        assert "steps recorded: 3" in report
        # measured HLO-ledger bytes render next to the ring model
        assert "HLO ledger" in report
        assert "ring-model prediction" in report
        assert "all-reduce" in report
        assert "## Health" in report
        assert "grad norm" in report

    def test_check_cli_smoke(self, run_jsonl, tmp_path):
        """tier-1 smoke of `report_run.py --check`: rc 0 on a clean file,
        non-zero on schema drift."""
        script = os.path.join(REPO, "scripts", "report_run.py")
        r = subprocess.run(
            [sys.executable, script, "--check", run_jsonl],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert "ok" in r.stdout
        # drifted copy: one record with an unknown field
        bad = str(tmp_path / "bad.jsonl")
        with open(run_jsonl) as f, open(bad, "w") as g:
            g.write(f.read())
            g.write(json.dumps(
                {"step": 99, "ts": 1.0, "not_a_metric": 1}
            ) + "\n")
        r = subprocess.run(
            [sys.executable, script, "--check", bad],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 1
        assert "not_a_metric" in r.stderr

    def test_check_cli_missing_file(self):
        script = os.path.join(REPO, "scripts", "report_run.py")
        r = subprocess.run(
            [sys.executable, script, "--check", "/nonexistent.jsonl"],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 2


class TestExampleEndToEnd:
    @pytest.mark.slow  # tier-1 budget: an example SUBPROCESS e2e like
    # the (slow) test_examples suite; report_run schema/render pins
    # stay quick above
    def test_ddp_example_renders_report(self, tmp_path):
        """Acceptance: scripts/report_run.py renders a markdown run report
        from a REAL examples/ddp run's JSONL, including measured
        (HLO-ledger) collective bytes alongside the comm_report model."""
        jsonl = str(tmp_path / "ddp_run.jsonl")
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", TINY_DS_NO_COMPILE_CACHE="1",
        )
        env.pop("XLA_FLAGS", None)  # the entry point sets its own device count
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "examples", "ddp",
                                          "train.py"),
             "--cpu-devices", "2", "--iters", "4", "--telemetry",
             "--metrics", jsonl],
            capture_output=True, text=True, timeout=420, env=env,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        assert "telemetry=on" in r.stdout
        counts, errs = schema.validate_file(jsonl)
        assert errs == []
        # run_meta + trace (span template) + straggler + telemetry_summary
        assert counts["step"] == 4 and counts["meta"] == 4
        rr = _load_report_run()
        metas, steps, _ = rr.load_run(jsonl)
        report = rr.render_report(metas, steps, source=jsonl)
        assert "HLO ledger" in report and "all-reduce" in report
        assert "ring-model prediction" in report
        assert "grad_allreduce_bytes" in report
        assert "steps recorded: 4" in report
        # measured bytes appear as a real magnitude, not zero
        meta = [m for m in metas if m.get("kind") == "run_meta"][0]
        assert meta["comm_measured"]["total_wire_bytes"] > 0
        assert meta["comm_model"]["grad_allreduce_bytes"] > 0
        assert meta["schema_version"] == schema.SCHEMA_VERSION
        # acceptance (ISSUE 5): trace_view.py emits valid Chrome-trace
        # JSON for this CPU-mesh ddp run, and every loop-resident
        # collective span carries wire bytes matching the hlo_comm ledger
        trace_json = str(tmp_path / "ddp_run.trace.json")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "trace_view.py"),
             jsonl, "-o", trace_json],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        doc = json.load(open(trace_json))
        assert doc["traceEvents"]
        ledger_loops = meta["comm_measured"]["wire_bytes_in_loops"]
        loop_spans = [
            e for e in doc["traceEvents"]
            if e.get("ph") == "X"
            and e.get("args", {}).get("loop_resident")
        ]
        assert loop_spans
        for e in loop_spans:
            assert e["args"]["wire_bytes"] == pytest.approx(
                ledger_loops[e["args"]["op"]], rel=1e-6,
            )


class TestBenchTelemetrySidecar:
    def _bench(self):
        spec = importlib.util.spec_from_file_location(
            "bench_telemetry_test", os.path.join(REPO, "bench.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_fresh_cycle_vs_baseline(self, tmp_path, monkeypatch):
        bench = self._bench()
        d = tmp_path / "repo"
        d.mkdir()
        monkeypatch.setattr(bench.os.path, "dirname", lambda p: str(d))
        assert bench._prev_round_value() is None       # trajectory []
        assert bench._vs_prev_round(1000.0) == 1.0     # explicit neutral
        (d / "BENCH_r01.json").write_text(json.dumps({"value": 500.0}))
        assert bench._prev_round_value() == 500.0
        assert bench._vs_prev_round(1000.0) == 2.0

    def test_rounds_order_numerically(self, tmp_path, monkeypatch):
        """Round files must sort by round NUMBER: lexicographically r9 >
        r10, which from round 10 on would compare the trajectory against
        the wrong round."""
        bench = self._bench()
        d = tmp_path / "repo"
        d.mkdir()
        monkeypatch.setattr(bench.os.path, "dirname", lambda p: str(d))
        (d / "BENCH_r9.json").write_text(json.dumps({"value": 900.0}))
        (d / "BENCH_r10.json").write_text(json.dumps({"value": 1000.0}))
        assert bench._prev_round_value() == 1000.0

    def test_sidecar_writes_valid_jsonl(self, tmp_path, ddp_off):
        bench = self._bench()
        path = str(tmp_path / "bench_telemetry.jsonl")
        state = ddp_off.init(jax.random.PRNGKey(0))
        batch = make_batch(5)
        compiled = ddp_off._step.lower(state, batch).compile()
        bench._write_bench_telemetry(
            path, ddp_off, state, batch, compiled.as_text(),
            "tiny", ddp_off.n_dev, 8, 32, 197e12, steps=2,
        )
        counts, errs = schema.validate_file(path)
        assert errs == []
        # run_meta + the trace span-template record
        assert counts["step"] == 2 and counts["meta"] == 2
        rr = _load_report_run()
        metas, steps, _ = rr.load_run(path)
        report = rr.render_report(metas, steps, source=path)
        assert "MFU" in report       # peak_flops_per_chip + n_params given
        assert "HLO ledger" in report
