# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Quantized gradient collectives (ZeroEngine grad_comm=, parallel/comm.py).

Pins the contract end to end: blockwise quant/dequant round-trip bounds and
stochastic-rounding unbiasedness, flat-vs-hierarchical schedule parity at
the shard_map level, grad_comm="fp32" HLO byte-identity (the knob is free
when off, same pattern as telemetry=None), int8/fp8 convergence parity
with and without error feedback, the measured-ledger >= 3.5x gradient wire
reduction (utils/hlo_comm.py), composition with ZeRO-2 / accumulation /
dynamic loss scaling, the unsupported-mesh validation errors, and the
telemetry gauges (comm bytes saved, residual norm)."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tiny_deepspeed_tpu import (
    AdamW, DDP, GPTConfig, GPT2Model, SingleDevice, Telemetry, Zero2, Zero3,
)
from tiny_deepspeed_tpu.parallel import comm as qcomm
from tiny_deepspeed_tpu.parallel.mesh import make_mesh
from tiny_deepspeed_tpu.utils.hlo_comm import collective_ledger

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)


def make_batch(seed=1, b=8, t=32, vocab=128, accum=None):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    shape = (accum, b, t) if accum else (b, t)
    return (jax.random.randint(k1, shape, 0, vocab),
            jax.random.randint(k2, shape, 0, vocab))


@pytest.fixture(scope="module")
def model():
    return GPT2Model(TINY)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

class TestQuantPrimitives:
    def test_padded_size(self):
        assert qcomm.padded_size(1, 8, 256) == 8 * 256
        assert qcomm.padded_size(8 * 256, 8, 256) == 8 * 256
        assert qcomm.padded_size(8 * 256 + 1, 8, 256) == 2 * 8 * 256

    @pytest.mark.parametrize("mode,tol", [("int8", 0.01), ("fp8", 0.05)])
    def test_round_trip(self, mode, tol):
        # blocks at wildly different magnitudes: the per-block scale is
        # what keeps the error relative to the BLOCK, not the tensor
        x = jax.random.normal(jax.random.PRNGKey(0), (16 * 256,))
        mags = jnp.repeat(10.0 ** jnp.arange(-8.0, 8.0), 256)
        x = x * mags
        q, s = qcomm.quantize_blockwise(x, mode, block=256)
        assert s.shape == (16, 1)
        deq = qcomm.dequantize_blockwise(q, s)
        err = float(jnp.linalg.norm(deq - x) / jnp.linalg.norm(x))
        assert err < tol, f"{mode} round-trip rel error {err}"

    def test_int8_stochastic_rounding_unbiased(self):
        # a fixed block whose values sit strictly BETWEEN int8 grid points:
        # nearest-rounding is maximally biased, stochastic must average out
        x = jnp.full((256,), 0.3) * jnp.linspace(0.1, 1.0, 256)
        x = x.at[0].set(1.0)  # pins the absmax -> fixed scale across draws
        scale = 1.0 / 127.0
        acc = np.zeros((256,), np.float64)
        draws = 500
        for i in range(draws):
            q, s = qcomm.quantize_blockwise(
                x, "int8", block=256, rng=jax.random.PRNGKey(i)
            )
            acc += np.asarray(qcomm.dequantize_blockwise(q, s), np.float64)
        mean = acc / draws
        # std of the mean ~ scale / sqrt(12 * draws) ~ 1e-4; 0.002 ~ 20 sigma
        assert float(np.max(np.abs(mean - np.asarray(x)))) < 0.002 * 127 * scale
        # and nearest rounding (rng=None) is deterministically different
        q0, s0 = qcomm.quantize_blockwise(x, "int8", block=256)
        q1, s1 = qcomm.quantize_blockwise(x, "int8", block=256)
        assert np.array_equal(np.asarray(q0), np.asarray(q1))

    def test_piece_owner_is_permutation(self):
        for n, inner in ((8, None), (8, 2), (8, 4), (16, 4)):
            owner = qcomm.piece_owner(n, inner)
            assert sorted(owner.tolist()) == list(range(n))
        assert qcomm.piece_owner(8, None).tolist() == list(range(8))

    def test_bad_mode_raises(self):
        with pytest.raises(ValueError):
            qcomm.quantize_blockwise(jnp.zeros((256,)), "fp4")

    def test_hier_groups_partition_the_axis(self):
        """Every rank appears exactly once per hop: intra groups tile the
        axis in consecutive runs, inter groups stride across them."""
        intra, inter = qcomm._hier_groups(8, 2)
        assert intra == [[0, 1], [2, 3], [4, 5], [6, 7]]
        assert inter == [[0, 2, 4, 6], [1, 3, 5, 7]]
        for n, inner in ((8, 4), (16, 4), (12, 3)):
            intra, inter = qcomm._hier_groups(n, inner)
            assert sorted(x for g in intra for x in g) == list(range(n))
            assert sorted(x for g in inter for x in g) == list(range(n))
            assert all(len(g) == inner for g in intra)
            assert all(len(g) == n // inner for g in inter)

    def test_hier_groups_degenerate_inner(self):
        """inner=1 (each rank its own group) and inner=n (one group) are
        the flat schedule's two degenerate factorizations — the callers
        bypass them (quantized_reduce_scatter treats them as flat), and
        piece_owner maps both to the identity."""
        intra, inter = qcomm._hier_groups(8, 1)
        assert intra == [[i] for i in range(8)]
        assert inter == [list(range(8))]
        intra, inter = qcomm._hier_groups(8, 8)
        assert intra == [list(range(8))]
        assert inter == [[i] for i in range(8)]
        assert qcomm.piece_owner(8, 1).tolist() == list(range(8))
        assert qcomm.piece_owner(8, 8).tolist() == list(range(8))

    def test_hier_groups_non_divisor_raises(self):
        """n % inner != 0 must raise, not silently drop the remainder
        ranks from every group."""
        with pytest.raises(ValueError, match="must divide"):
            qcomm._hier_groups(8, 3)
        with pytest.raises(ValueError, match="must divide"):
            qcomm._hier_groups(8, 0)
        with pytest.raises(ValueError, match="must divide"):
            qcomm.piece_owner(8, 5)

    @pytest.mark.parametrize("mode,stochastic", [
        ("int8", False), ("int8", True), ("fp8", False),
    ])
    def test_pallas_quantizer_matches_xla(self, mode, stochastic):
        """The fused Pallas quantizer (ops/quant_pallas.py, interpret
        mode) is the same function as the XLA path — both consume one
        dither draw, so codes and scales agree exactly."""
        from tiny_deepspeed_tpu.ops import quant_pallas
        from tiny_deepspeed_tpu.ops.dispatch import kernel_target_forced
        x = jax.random.normal(jax.random.PRNGKey(2), (16 * 256,))
        x = x * jnp.repeat(10.0 ** jnp.arange(-8.0, 8.0), 256)
        rng = jax.random.PRNGKey(9) if stochastic else None
        qx, sx = qcomm.quantize_blockwise(x, mode, 256, rng)
        old = quant_pallas._INTERPRET
        quant_pallas._INTERPRET = True
        try:
            with kernel_target_forced("tpu"):
                qp, sp = qcomm.quantize_blockwise(x, mode, 256, rng)
        finally:
            quant_pallas._INTERPRET = old
        assert qp.dtype == qx.dtype and qp.shape == qx.shape
        np.testing.assert_allclose(np.asarray(sp), np.asarray(sx),
                                   rtol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(qp, np.float32), np.asarray(qx, np.float32)
        )


# ---------------------------------------------------------------------------
# the schedule, straight at the shard_map level
# ---------------------------------------------------------------------------

class TestSchedule:
    @pytest.mark.parametrize("inner", [None, 2, 4])
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_sync_matches_exact_mean(self, mode, inner):
        mesh = make_mesh()
        n = mesh.shape["data"]
        e = n * 256 * 2
        xg = jax.random.normal(jax.random.PRNGKey(3), (n, e)) * 0.1
        xg = jax.device_put(xg, NamedSharding(mesh, P("data")))
        exact = np.asarray(jnp.mean(xg, axis=0))

        def local(x):
            tree = {"g": x[0]}
            red, res = qcomm.quantized_grad_sync(
                tree, None, "data", n, mode, block=256, inner=inner,
                rng=jax.random.PRNGKey(7) if mode == "int8" else None,
            )
            assert res is None
            return red["g"][None]

        out = jax.jit(jax.shard_map(
            local, mesh=mesh, in_specs=(P("data"),),
            out_specs=P("data"), check_vma=False,
        ))(xg)
        got = np.asarray(out[0])
        rel = float(np.linalg.norm(got - exact) / np.linalg.norm(exact))
        # two quantization hops (RS + AG); int8 blockwise keeps ~1e-2,
        # e4m3's 3 mantissa bits land around 4-6% per hop
        tol = 0.03 if mode == "int8" else 0.12
        assert rel < tol, f"{mode} inner={inner}: rel {rel}"

    def test_error_feedback_residual_is_what_was_dropped(self):
        mesh = make_mesh()
        n = mesh.shape["data"]
        e = n * 256
        xg = jax.random.normal(jax.random.PRNGKey(5), (n, e))
        xg = jax.device_put(xg, NamedSharding(mesh, P("data")))
        res0 = jax.device_put(
            jnp.zeros((n, e)), NamedSharding(mesh, P("data"))
        )

        def local(x, r):
            tree = {"g": x[0]}
            red, res = qcomm.quantized_grad_sync(
                tree, r[0], "data", n, "int8", block=256,
            )
            return red["g"][None], res[None]

        _, res = jax.jit(jax.shard_map(
            local, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=(P("data"), P("data")), check_vma=False,
        ))(xg, res0)
        res = np.asarray(res)
        x = np.asarray(xg)
        # residual == local grad minus its own dequantized transmission:
        # recompute per-row and compare.  Exact equality up to rounding
        # TIES — jit fusion may reassociate the absmax and break a .5 tie
        # the other way, moving single elements by one full quant step
        blocks = np.abs(x).reshape(n, -1, 256).max(axis=2)
        step = blocks / 127.0  # int8 step per block
        for i in range(n):
            q, s = qcomm.quantize_blockwise(jnp.asarray(x[i]), "int8", 256)
            expect = x[i] - np.asarray(qcomm.dequantize_blockwise(q, s))
            diff = np.abs(res[i] - expect).reshape(-1, 256)
            assert (diff <= step[i][:, None] * 1.01 + 1e-6).all()
            assert (diff > 1e-6).mean() < 0.005  # ties are rare
        # bounded by half an int8 step of the block absmax (+ tie slack)
        bound = (step * 0.51 + 1e-6)[:, :, None]
        assert (np.abs(res.reshape(n, -1, 256)) <= bound).all()


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

def run_curve(model, eng_cls=DDP, steps=20, seed=1, **kw):
    eng = eng_cls(model, AdamW(lr=1e-3), **kw)
    state = eng.init(jax.random.PRNGKey(0))
    batch = make_batch(seed, accum=kw.get("accum_steps"))
    losses = []
    for _ in range(steps):
        state, loss = eng.step(state, batch)
        losses.append(float(loss))
    return losses, state, eng


class TestEngineGradComm:
    def test_fp32_hlo_byte_identical(self, model):
        """grad_comm="fp32" is FREE: the compiled step program is the same
        bytes as an un-knobbed engine (the telemetry=None pattern)."""
        e0 = DDP(model, AdamW(lr=1e-3))
        e1 = DDP(model, AdamW(lr=1e-3), grad_comm="fp32")
        s0 = e0.init(jax.random.PRNGKey(0))
        s1 = e1.init(jax.random.PRNGKey(0))
        batch = make_batch()
        assert s1.grad_residual is None
        assert e0._step.lower(s0, batch).as_text() \
            == e1._step.lower(s1, batch).as_text()

    # tier-1 budget (scripts/tier1_times.py): the fp8 codec is pinned at
    # the primitive level (TestQuantPrimitives) and rides the same
    # schedule as int8 — its 20-step curve runs in the full tier
    @pytest.mark.parametrize("mode", [
        "int8", pytest.param("fp8", marks=pytest.mark.slow),
    ])
    def test_convergence_parity_with_error_feedback(self, model, mode):
        base, _, _ = run_curve(model, steps=20)
        quant, state, _ = run_curve(model, steps=20, grad_comm=mode)
        rel = [abs(a - b) / a for a, b in zip(base, quant)]
        assert max(rel) < 0.05, f"max divergence {max(rel):.4f}"
        assert quant[-1] < quant[0] - 0.1  # and it actually trains
        # the residual is alive: nonzero, finite, bounded
        res = np.asarray(state.grad_residual)
        assert res.shape[0] == 8 and np.isfinite(res).all()
        assert 0 < float(np.abs(res).max())

    @pytest.mark.slow  # tier-1 budget: negative-space complement of the
    # with-EF parity above (which stays quick); 40 steps of curves
    def test_convergence_without_error_feedback(self, model):
        base, _, _ = run_curve(model, steps=20)
        quant, state, _ = run_curve(
            model, steps=20, grad_comm="int8",
            grad_comm_error_feedback=False,
        )
        assert state.grad_residual is None
        rel = [abs(a - b) / a for a, b in zip(base, quant)]
        assert max(rel) < 0.10
        assert quant[-1] < quant[0] - 0.1

    @pytest.mark.slow  # tier-1 budget: 2-hop vs flat parity is pinned
    # quick at the shard_map level (TestSchedule); the 20-step engine
    # curve runs in the full tier
    def test_hierarchical_2hop_tracks_flat(self, model):
        flat, _, _ = run_curve(model, steps=10, grad_comm="int8")
        hier, _, eng = run_curve(model, steps=10, grad_comm="int8",
                                 grad_comm_groups=4)
        rel = [abs(a - b) / a for a, b in zip(flat, hier)]
        assert max(rel) < 0.02
        assert "2-hop inner=4" in eng.describe()

    def test_ledger_gradient_wire_drops_4x(self, model):
        """The acceptance number: int8 grad_comm cuts the measured
        (post-SPMD HLO ledger) collective wire >= 3.5x vs fp32 — under
        DDP the gradient all-reduce IS essentially all the wire."""
        batch = make_batch()
        e0 = DDP(model, AdamW(lr=1e-3))
        s0 = e0.init(jax.random.PRNGKey(0))
        led_f = collective_ledger(
            e0._step.lower(s0, batch).compile().as_text()
        )
        eq = DDP(model, AdamW(lr=1e-3), grad_comm="int8")
        sq = eq.init(jax.random.PRNGKey(0))
        led_q = collective_ledger(
            eq._step.lower(sq, batch).compile().as_text()
        )
        assert not led_f["unresolved_groups"]
        assert not led_q["unresolved_groups"]
        ratio = led_f["total_wire_bytes"] / led_q["total_wire_bytes"]
        assert ratio >= 3.5, (
            f"wire only dropped {ratio:.2f}x: "
            f"{led_f['wire_bytes']} -> {led_q['wire_bytes']}"
        )
        # the honest per-dtype view: the quantized step's wire is
        # dominated by 1-byte values, not the f32 scales riding along
        by_dt = led_q["wire_bytes_by_dtype"]
        assert by_dt.get("s8", 0) > 0.6 * sum(by_dt.values())

    def test_zero2_composes_and_trains(self, model):
        losses, state, eng = run_curve(model, eng_cls=Zero2, steps=8,
                                       grad_comm="int8")
        assert losses[-1] < losses[0]
        batch = make_batch()
        txt = eng._step.lower(state, batch).compile().as_text()
        assert "all-to-all" in txt  # the explicit quantized schedule ran

    def test_accum_composes(self, model):
        base, _, _ = run_curve(model, steps=8, accum_steps=2)
        quant, _, _ = run_curve(model, steps=8, accum_steps=2,
                                grad_comm="int8")
        rel = [abs(a - b) / a for a, b in zip(base, quant)]
        assert max(rel) < 0.05

    def test_dynamic_loss_scale_composes(self, model):
        losses, state, _ = run_curve(model, steps=8, grad_comm="int8",
                                     loss_scale="dynamic")
        assert losses[-1] < losses[0]
        assert np.isfinite(np.asarray(state.grad_residual)).all()

    def test_overflow_step_rolls_back_residual(self, model):
        """A dynamic-scaling overflow step discards the whole update — the
        consumed error-feedback residual must roll back with it, or the
        deferred gradient signal is lost on every scale-halving step."""
        eng = DDP(model, AdamW(lr=1e-3), grad_comm="int8",
                  loss_scale="dynamic")
        state = eng.init(jax.random.PRNGKey(0))
        state, _ = eng.step(state, make_batch())  # residual now nonzero
        res_before = np.asarray(state.grad_residual).copy()
        assert float(np.abs(res_before).max()) > 0
        params = dict(state.params)
        name = next(iter(params))
        params[name] = jnp.full_like(params[name], jnp.nan)
        poisoned = state.replace(params=params)
        new, _ = eng.step(poisoned, make_batch())
        assert float(new.scaler["scale"]) == 2.0 ** 14  # overflow detected
        np.testing.assert_array_equal(
            np.asarray(new.grad_residual), res_before
        )

    def test_single_device_inert_with_warning(self, model):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            eng = SingleDevice(model, AdamW(lr=1e-3), grad_comm="int8")
        assert any("inert" in str(x.message) for x in w)
        assert not eng._grad_comm_active
        state = eng.init(jax.random.PRNGKey(0))
        assert state.grad_residual is None
        state, loss = eng.step(state, make_batch())
        assert np.isfinite(float(loss))

    def test_unsupported_configs_raise(self, model):
        with pytest.raises(ValueError, match="grad_comm must be"):
            DDP(model, AdamW(lr=1e-3), grad_comm="int4")
        # the old "stages 0-2" refusal is LIFTED: ZeRO-3 + quantized
        # grads now lowers to the composed scheduler (the implicit
        # on-demand gather slot supplies the in-region weight gathers)
        eng = Zero3(model, AdamW(lr=1e-3), grad_comm="int8")
        assert eng._lowering == "composed"
        with pytest.raises(ValueError, match="pure data-parallel"):
            DDP(model, AdamW(lr=1e-3), grad_comm="int8",
                tensor_parallel=2)
        with pytest.raises(ValueError, match="proper divisor"):
            DDP(model, AdamW(lr=1e-3), grad_comm="int8",
                grad_comm_groups=3)
        with pytest.raises(ValueError, match="requires grad_comm"):
            DDP(model, AdamW(lr=1e-3), grad_comm_groups=4)

    @pytest.mark.slow  # tier-1 budget: residual save/restore (kept,
    # re-derived, zero-filled) is pinned quick in test_resilience's
    # elastic suite; the same-topology roundtrip runs in the full tier
    def test_checkpoint_roundtrip_carries_residual(self, model, tmp_path):
        from tiny_deepspeed_tpu.utils.checkpoint import (
            load_checkpoint, save_checkpoint,
        )
        _, state, eng = run_curve(model, steps=3, grad_comm="int8")
        save_checkpoint(str(tmp_path), state, step=3)
        restored = load_checkpoint(str(tmp_path), eng)
        np.testing.assert_array_equal(
            np.asarray(state.grad_residual), np.asarray(restored.grad_residual)
        )
        # and a legacy (residual-free) checkpoint resumes with zeros
        fp32_eng = DDP(model, AdamW(lr=1e-3))
        fp32_state = fp32_eng.init(jax.random.PRNGKey(0))
        save_checkpoint(str(tmp_path), fp32_state, step=10)
        resumed = load_checkpoint(str(tmp_path), eng, step=10)
        assert resumed.grad_residual is not None
        assert float(np.abs(np.asarray(resumed.grad_residual)).max()) == 0.0
        state2, loss = eng.step(resumed, make_batch())
        assert np.isfinite(float(loss))

    @pytest.mark.slow  # tier-1 budget: gauge names are drift-guarded
    # in test_repo_hygiene; the wire numbers are pinned quick by
    # test_ledger_gradient_wire_drops_4x
    def test_telemetry_gauges(self, model):
        telem = Telemetry()
        eng = DDP(model, AdamW(lr=1e-3), grad_comm="int8",
                  telemetry=telem)
        state = eng.init(jax.random.PRNGKey(0))
        batch = make_batch()
        state, _ = eng.step(state, batch)
        out = eng.telemetry.capture_compiled(state, batch)
        assert out["grad_comm"]["mode"] == "int8"
        saved = telem.gauge("grad_comm_wire_saved_bytes")
        assert saved is not None and saved > 0
        norm = telem.sample_grad_residual(state)
        assert norm is not None and norm > 0
        assert telem.gauge("grad_residual_norm") == norm
        # model report knows the schedule replaced the fp32 collective
        rep = out["comm_model"]
        assert rep["grad_comm"] == "int8"
        assert rep["grad_quant_sync_bytes"] > 0
        assert rep["grad_allreduce_bytes"] == 0.0
        # fp32 state has no residual to sample
        assert Telemetry().sample_grad_residual(
            DDP(model, AdamW(lr=1e-3)).init(jax.random.PRNGKey(0))
        ) is None
