# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Every entry point runs end-to-end on the virtual CPU mesh in seconds.

The reference's de-facto test suite is "run the five train scripts under
torchrun and watch the loss" (SURVEY §4); this makes that an actual test:
each example executes as a subprocess with `--cpu-devices 8 --iters 2`,
which auto-selects the `tiny` preset (examples/common.py) so XLA-CPU
compiles stay in the seconds range (round-1 verdict weak #7)."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # multi-minute composition suite (see pytest.ini)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    "single_device", "ddp", "zero1", "zero2", "zero3", "pipeline",
]


def _losses(stdout):
    return {
        int(ln.split()[1]): float(ln.split()[-1])
        for ln in stdout.splitlines() if ln.startswith("iter ")
    }


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    """Checkpoint in anger (round-1 verdict #9): train 6 iters straight;
    separately train 3 iters + save, then --resume to 6.  The resumed
    trajectory must equal the uninterrupted one (sharded Orbax restore into
    engine shardings + data-stream fast-forward)."""
    def run(*extra):
        proc = subprocess.run(
            [sys.executable, os.path.join("examples", "zero2", "train.py"),
             "--cpu-devices", "8", "--lr", "1e-3", *extra],
            cwd=REPO, capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        return _losses(proc.stdout)

    straight = run("--iters", "6")
    ck = str(tmp_path / "ck")
    first = run("--iters", "3", "--save-every", "3", "--save-dir", ck)
    resumed = run("--iters", "6", "--resume", "--save-dir", ck)
    assert set(first) == {0, 1, 2} and set(resumed) == {3, 4, 5}
    for it in (3, 4, 5):
        assert abs(resumed[it] - straight[it]) < 2e-4, (
            it, resumed[it], straight[it]
        )


def test_profile_and_metrics_flags(tmp_path):
    """--profile writes an XPlane trace dir; --metrics a JSONL with loss/
    step_s/tokens_per_s per iter (SURVEY §5.1/§5.5 observability wired
    into the entry points)."""
    import json
    prof = str(tmp_path / "prof")
    metr = str(tmp_path / "m.jsonl")
    proc = subprocess.run(
        [sys.executable, os.path.join("examples", "ddp", "train.py"),
         "--cpu-devices", "8", "--iters", "6",
         "--profile", prof, "--metrics", metr],
        cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.path.isdir(os.path.join(prof, "plugins", "profile"))
    recs = [json.loads(ln) for ln in open(metr)]
    assert len(recs) == 6
    assert {"loss", "step_s", "tokens_per_s"} <= set(recs[0])


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_smoke(name):
    proc = subprocess.run(
        [sys.executable, os.path.join("examples", name, "train.py"),
         "--cpu-devices", "8", "--iters", "2"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "done: 2 iters" in proc.stdout, proc.stdout[-2000:]
    # fresh-init loss on the tiny preset ≈ ln(512) ≈ 6.24
    first = float(
        [ln for ln in proc.stdout.splitlines() if ln.startswith("iter ")][0]
        .split()[-1]
    )
    assert 5.0 < first < 8.0, proc.stdout[-2000:]


def test_example_llama_family():
    """Any entry point accepts the llama-* presets (one flat namespace)."""
    proc = subprocess.run(
        [sys.executable, os.path.join("examples", "zero3", "train.py"),
         "--cpu-devices", "8", "--iters", "2", "--model", "llama-tiny",
         "--seq-len", "64"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "model=llama-tiny" in proc.stdout
    assert "done: 2 iters" in proc.stdout, proc.stdout[-2000:]


@pytest.mark.parametrize("model", ["tiny", "llama-tiny"])
def test_generate_entry_point(model):
    """examples/generate.py samples from both families without a ckpt."""
    proc = subprocess.run(
        [sys.executable, os.path.join("examples", "generate.py"),
         "--cpu", "--model", model, "--max-new-tokens", "8"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "generated=" in proc.stdout, proc.stdout[-2000:]


def test_train_feature_flags():
    """--lr-schedule/--warmup-steps/--grad-clip/--loss-scale reach the
    engine from any entry point (schedules, clipping, and AMP are
    capabilities the reference lacks — reference README.md:68 TODO)."""
    proc = subprocess.run(
        [sys.executable, os.path.join("examples", "zero1", "train.py"),
         "--cpu-devices", "8", "--iters", "4",
         "--lr-schedule", "warmup_cosine", "--warmup-steps", "2",
         "--grad-clip", "1.0", "--loss-scale", "dynamic"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    losses = _losses(proc.stdout)
    assert len(losses) == 4
    import math
    assert all(map(math.isfinite, losses.values()))


def test_example_moe_family_with_ep():
    """moe-* presets reachable from every entry point; --expert-parallel
    carves the 'expert' mesh axis (review r2: MoE was engine-only before)."""
    proc = subprocess.run(
        [sys.executable, os.path.join("examples", "zero2", "train.py"),
         "--cpu-devices", "8", "--iters", "2", "--model", "moe-tiny",
         "--expert-parallel", "2", "--seq-len", "128"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "model=moe-tiny" in proc.stdout
    assert "done: 2 iters" in proc.stdout, proc.stdout[-2000:]
