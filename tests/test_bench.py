# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""bench.py harness logic — the driver-facing surface that produced 0.0 in
rounds 1 AND 2.  These tests pin the failure-path behavior (retry/diagnose,
last-good cache, config gating) WITHOUT a TPU: everything here is pure
process/JSON logic; run_one/run_decode need the chip and are not imported."""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    """A fresh bench module whose last-good cache lives in tmp_path."""
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "LAST_GOOD",
                        str(tmp_path / "last_good.json"))
    for var in ("BENCH_BATCH", "BENCH_SEQ", "BENCH_DECODE", "BENCH_MODEL",
                "BENCH_ATTEMPT", "BENCH_OFFLOAD", "BENCH_AUTOTUNE",
                "BENCH_MOE_DISPATCH"):
        monkeypatch.delenv(var, raising=False)
    return mod


def _diagnose(bench, exc, capsys):
    with pytest.raises(SystemExit) as e:
        bench._retry_or_diagnose(exc)
    assert e.value.code == 0  # the driver must see rc 0 + one JSON line
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


class TestDiagnose:
    def test_final_failure_emits_zero_record(self, bench, capsys,
                                             monkeypatch):
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        rec = _diagnose(bench, RuntimeError("UNAVAILABLE: x"), capsys)
        assert rec["value"] == 0.0 and rec["extra"]["transient"]

    def test_deterministic_failure_never_replays_cache(self, bench, capsys,
                                                       monkeypatch):
        """A compile OOM must surface as 0.0 even with a healthy cache —
        replaying would mask a real regression (round-3 review)."""
        bench._save_last_good({
            "metric": "gpt2-124m_train_tokens_per_sec_per_chip",
            "value": 88000.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        })
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        rec = _diagnose(bench, RuntimeError("RESOURCE_EXHAUSTED: hbm"),
                        capsys)
        assert rec["value"] == 0.0 and not rec["extra"]["transient"]

    def test_transient_failure_replays_cache_labeled(self, bench, capsys,
                                                     monkeypatch):
        bench._save_last_good({
            "metric": "gpt2-124m_train_tokens_per_sec_per_chip",
            "value": 88000.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        })
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        rec = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
        assert rec["value"] == 88000.0
        assert rec["extra"]["cached_result"] is True
        assert rec["extra"]["measured_commit"]
        assert "live_error" in rec["extra"]
        # TOP-LEVEL staleness: a substituted cache is not a live
        # measurement — trajectory tooling must not treat it as fresh
        assert rec["stale"] is True

    def test_cache_ignored_for_non_default_config(self, bench, capsys,
                                                  monkeypatch):
        bench._save_last_good({
            "metric": "gpt2-124m_train_tokens_per_sec_per_chip",
            "value": 88000.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        })
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        monkeypatch.setenv("BENCH_SEQ", "4096")
        rec = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
        assert rec["value"] == 0.0

    def test_decode_failure_uses_decode_metric_no_cache(self, bench,
                                                        capsys,
                                                        monkeypatch):
        bench._save_last_good({
            "metric": "gpt2-124m_train_tokens_per_sec_per_chip",
            "value": 88000.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        })
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        monkeypatch.setenv("BENCH_DECODE", "1")
        rec = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
        assert rec["metric"].endswith("_decode_tokens_per_sec")
        assert rec["value"] == 0.0


class TestCache:
    def test_pre_knob_record_still_replays(self, bench, capsys,
                                           monkeypatch):
        """Adding a knob to _config_fingerprint must NOT invalidate
        records saved before the knob existed (round 4: adding
        moe_dispatch made the committed record string-unequal and the
        replay path silently returned 0.0 — the exact failure the cache
        exists to prevent).  Absent keys compare as the knob default; a
        CURRENT non-default knob still blocks the replay, and a
        corrupted fingerprint (non-dict JSON) never replays or raises."""
        import json as _json
        bench._save_last_good({
            "metric": "gpt2-124m_train_tokens_per_sec_per_chip",
            "value": 88000.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        })
        # simulate "saved before the newest knob existed": drop one key
        rec = _json.load(open(bench.LAST_GOOD))
        fp = _json.loads(rec["config_fingerprint"])
        fp.pop("moe_dispatch")
        rec["config_fingerprint"] = _json.dumps(fp, sort_keys=True)
        _json.dump(rec, open(bench.LAST_GOOD, "w"))
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        out = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
        assert out["value"] == 88000.0          # replays despite old format
        # but a CURRENT non-default knob still blocks the replay
        monkeypatch.setenv("BENCH_MOE_DISPATCH", "sort")
        out = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
        assert out["value"] == 0.0
        monkeypatch.delenv("BENCH_MOE_DISPATCH")
        # corrupted committed record: no replay, NO exception (driver
        # contract: one JSON line, rc 0)
        for bad in (5,        # json.loads(5) -> TypeError
                    "x",      # invalid JSON -> ValueError
                    "[]"):    # valid JSON, non-dict -> isinstance guard
            rec["config_fingerprint"] = bad
            _json.dump(rec, open(bench.LAST_GOOD, "w"))
            out = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
            assert out["value"] == 0.0

    def test_roundtrip_and_staleness(self, bench):
        rec = {"metric": "gpt2-124m_train_tokens_per_sec_per_chip",
               "value": 1.0, "unit": "tokens/s/chip", "vs_baseline": 1.0}
        bench._save_last_good(rec)
        got, stale = bench._load_last_good()
        assert got["value"] == 1.0 and got["measured_commit"]
        assert not stale
        # past the age window the record STILL loads (round-boundary
        # insurance) but is flagged stale for honest labeling
        saved = json.load(open(bench.LAST_GOOD))
        saved["measured_at_epoch"] = time.time() - bench.MAX_CACHE_AGE_S - 1
        json.dump(saved, open(bench.LAST_GOOD, "w"))
        got, stale = bench._load_last_good()
        assert got["value"] == 1.0 and stale

    def test_stale_replay_is_labeled(self, bench, capsys, monkeypatch):
        """A round-long outage replays the committed measurement with
        stale_cached_result + age_hours — never a silent fresh-looking
        number, never 0.0 (the round-1..3 failure)."""
        bench._save_last_good({
            "metric": "gpt2-124m_train_tokens_per_sec_per_chip",
            "value": 88000.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        })
        saved = json.load(open(bench.LAST_GOOD))
        saved["measured_at_epoch"] = time.time() - bench.MAX_CACHE_AGE_S - 60
        json.dump(saved, open(bench.LAST_GOOD, "w"))
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        rec = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
        assert rec["value"] == 88000.0
        assert rec["stale"] is True
        assert rec["extra"]["stale_cached_result"] is True
        assert rec["extra"]["age_hours"] >= 14
        assert "note" in rec["extra"]

    def test_fingerprint_mismatch_never_replays(self, bench, capsys,
                                                monkeypatch):
        """A record saved under BENCH_AUTOTUNE must not replay as the
        default config's measurement (round-3 advice)."""
        monkeypatch.setenv("BENCH_AUTOTUNE", "1")
        bench._save_last_good({
            "metric": "gpt2-124m_train_tokens_per_sec_per_chip",
            "value": 99000.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        })
        monkeypatch.delenv("BENCH_AUTOTUNE")
        assert bench._load_last_good() is None
        monkeypatch.setenv("BENCH_ATTEMPT", str(bench.MAX_ATTEMPTS))
        rec = _diagnose(bench, RuntimeError("UNAVAILABLE: hung"), capsys)
        assert rec["value"] == 0.0

    def test_default_config_predicate(self, bench, monkeypatch):
        assert bench._default_config()
        monkeypatch.setenv("BENCH_OFFLOAD", "1")
        assert not bench._default_config()
        monkeypatch.delenv("BENCH_OFFLOAD")
        monkeypatch.setenv("BENCH_BATCH", "12")
        assert not bench._default_config()
        monkeypatch.delenv("BENCH_BATCH")
        monkeypatch.setenv("BENCH_AUTOTUNE", "1")
        assert not bench._default_config()
        monkeypatch.delenv("BENCH_AUTOTUNE")
        monkeypatch.setenv("BENCH_MODEL", "gpt2-1.5b")
        assert not bench._default_config()

    def test_vs_prev_round_reads_latest_nonzero(self, bench, monkeypatch,
                                                tmp_path):
        d = tmp_path / "repo"
        d.mkdir()
        (d / "BENCH_r01.json").write_text(json.dumps({"value": 0.0}))
        (d / "BENCH_r02.json").write_text(json.dumps({"value": 50000.0}))
        monkeypatch.setattr(bench.os.path, "dirname",
                            lambda p: str(d))
        assert bench._vs_prev_round(100000.0) == 2.0


def test_probe_timeout_raises_transient_signature():
    """_devices_with_timeout against a hanging subprocess must raise the
    UNAVAILABLE signature the retry path matches."""
    spec = importlib.util.spec_from_file_location(
        "bench_probe", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    real_run = subprocess.run

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1)

    subprocess.run = fake_run
    try:
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            mod._devices_with_timeout(1)
    finally:
        subprocess.run = real_run
