# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""ZeRO-3 layer-ahead weight-gather prefetch (ZeroEngine gather_prefetch=,
parallel/schedule.GatherPrefetchScan, utils/hlo_comm.overlap_report gather side).

Pins the contract end to end: gather_prefetch off (and K=1) HLO
byte-identical to the on-demand zero3 program on the fp32 AND fp8-gather
paths, 20-step loss parity with the unprefetched schedule (fp32 within
1e-4, fp8 within 5%), the hierarchical 2-hop gather (gather_groups=)
parity + its bytes-identity-unless-dtype-changes property, loop-resident
all-gather wire > 0 on the 8-device CPU mesh with the ledger tracking
comm_report's prefetch pricing, the gather_overlap_frac telemetry gauge +
gather_overlap run_meta record, composition with accumulation / dropout /
dynamic loss scaling / Llama (slow tier), and the validation errors —
plus the round-8 satellites (offload_prefetch validated instead of
clamped; the grad_buckets x gather_quant refusal points at
gather_prefetch).

Wall-time discipline: every module-scoped run compiles its step ONCE
(engine._step.lower(...).compile()) and drives the loss curve through
the compiled executable, so the 20-step parity pins cost one XLA compile
each, not two."""

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import (
    AdamW, DDP, GPTConfig, GPT2Model, LlamaConfig, LlamaModel, Telemetry,
    Zero2, Zero3,
)
from tiny_deepspeed_tpu.parallel import comm as qcomm
from tiny_deepspeed_tpu.parallel.mesh import make_mesh
from tiny_deepspeed_tpu.utils.hlo_comm import (
    collective_ledger, overlap_report,
)
from tiny_deepspeed_tpu.utils.profiling import comm_report

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)
TINY_Q = dataclasses.replace(TINY, gather_quant="fp8")

# the analyzer's gathering classification is all-gather ONLY (ring/pipe
# collective-permutes are activation traffic — hlo_comm._GATHER_OPS note)
_GATHERING = ("all-gather",)


def make_batch(seed=1, b=8, t=32, vocab=128, accum=None):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    shape = (accum, b, t) if accum else (b, t)
    return (jax.random.randint(k1, shape, 0, vocab),
            jax.random.randint(k2, shape, 0, vocab))


def exec_curve(model, steps, keep_text=False, seed=1, **kw):
    """Build the engine, compile its step ONCE, drive `steps` iterations
    through the compiled executable.  Returns a dict with the engine,
    loss curve, final state, and (optionally) the compiled HLO text —
    one backend compile per call however many consumers share it."""
    eng = Zero3(model, AdamW(lr=1e-3), **kw)
    state = eng.init(jax.random.PRNGKey(0))
    batch = make_batch(seed, accum=kw.get("accum_steps"))
    ex = eng._step.lower(state, batch).compile()
    text = ex.as_text() if keep_text else None
    losses = []
    for _ in range(steps):
        state, loss = ex(state, batch)
        losses.append(float(loss))
    return {"eng": eng, "losses": losses, "state": state, "text": text,
            "batch": batch}


def _rel(base, other):
    return max(abs(a - b) / a for a, b in zip(base, other))


@pytest.fixture(scope="module")
def model():
    return GPT2Model(TINY)


@pytest.fixture(scope="module")
def qmodel():
    return GPT2Model(TINY_Q)


@pytest.fixture(scope="module")
def fp32_base(model):
    return exec_curve(model, 20, keep_text=True)


@pytest.fixture(scope="module")
def fp32_pf(model):
    return exec_curve(model, 20, keep_text=True, gather_prefetch=2)


@pytest.fixture(scope="module")
def fp8_base(qmodel):
    return exec_curve(qmodel, 20)


@pytest.fixture(scope="module")
def fp8_pf(qmodel):
    return exec_curve(qmodel, 20, gather_prefetch=2)


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

class TestEngineGatherPrefetch:
    def test_off_hlo_byte_identical(self, model, qmodel):
        """gather_prefetch off (and K=1) is FREE: the traced step program
        is the same bytes as an un-knobbed zero3 engine, on the fp32 AND
        fp8-gather paths (the acceptance pin)."""
        def lowered(mdl, **kw):
            eng = Zero3(mdl, AdamW(lr=1e-3), **kw)
            state = eng.init(jax.random.PRNGKey(0))
            return eng._step.lower(state, make_batch()).as_text()

        base = lowered(model)
        assert base == lowered(model, gather_prefetch=0)
        assert base == lowered(model, gather_prefetch=1)
        assert lowered(qmodel) == lowered(qmodel, gather_prefetch=1)

    def test_loss_parity_fp32(self, fp32_base, fp32_pf):
        """The acceptance bound: 20-step loss parity vs unprefetched
        zero3 within 1e-4 (fp32) — the prefetched scan is the same math,
        only the gather placement moves."""
        base, pf = fp32_base["losses"], fp32_pf["losses"]
        assert _rel(base, pf) < 1e-4, f"max divergence {_rel(base, pf)}"
        assert pf[-1] < pf[0] - 0.1  # and it actually trains
        assert "gather_prefetch=2" in fp32_pf["eng"].describe()

    def test_loss_parity_fp8(self, fp8_base, fp8_pf):
        """...and within 5% on the fp8-gather path (composes with
        gather_quant: the prefetched gathers move the same f8 leaves)."""
        base, pf = fp8_base["losses"], fp8_pf["losses"]
        assert _rel(base, pf) < 0.05, f"max divergence {_rel(base, pf)}"
        assert pf[-1] < pf[0] - 0.1

    def test_gather_overlap_loop_resident(self, fp32_base, fp32_pf):
        """THE acceptance property: on the 8-device CPU mesh the
        prefetched step keeps loop-resident all-gather wire > 0 (the
        per-layer gathers stay inside the scan — a hoist regression,
        which would regrow full-model HBM, reads 0) and the analyzer's
        gather side reports it."""
        rep = overlap_report(fp32_pf["text"])
        assert rep["gather_wire_bytes_in_loops"] > 0
        assert rep["gather_wire_bytes_total"] > 0
        assert rep["gather_overlap_frac"] > 0.4
        assert rep["loop_collective_counts"].get("all-gather", 0) >= 2
        # the on-demand program keeps the property too (GSPMD emits the
        # gathers in-loop by construction) — the analyzer sees both
        rep0 = overlap_report(fp32_base["text"])
        assert rep0["gather_overlap_frac"] > 0.0

    def test_ledger_tracks_comm_report_pricing(self, model, fp32_pf):
        """comm_report prices the prefetch (K-1 extra clamped gathers per
        pass, (L+K-1)/L on the block term) — and because the schedule is
        now EXPLICIT, the compiled ledger tracks the model tightly where
        the GSPMD on-demand program deviates ~1.8x on this backend
        (PROFILE.md "Gather window")."""
        eng0 = Zero3(model, AdamW(lr=1e-3))  # construction only, no jit
        r0 = comm_report(eng0)
        r2 = comm_report(fp32_pf["eng"])
        assert r2["gather_prefetch"] == 2 and r0["gather_prefetch"] == 0
        assert r2["zero3_layer_gather_bytes"] > \
            r0["zero3_layer_gather_bytes"]
        led = collective_ledger(fp32_pf["text"])
        assert not led["unresolved_groups"]
        measured = sum(
            led["wire_bytes"].get(op, 0.0) for op in _GATHERING
        )
        predicted = r2["zero3_layer_gather_bytes"]
        assert abs(measured - predicted) <= 0.10 * predicted, \
            (measured, predicted)

    def test_telemetry_gauge_and_schema(self, fp32_pf):
        """The gauge/record WIRING, compile-free in tier-1: feed the
        already-compiled prefetched HLO through the same overlap_report
        the telemetry gauge reads, and pin the run_meta record's schema
        legality (the full capture_compiled round trip — which re-AOT-
        compiles the step — runs in the slow composition tier)."""
        rep = overlap_report(fp32_pf["text"])
        rec = {
            k: rep[k] for k in (
                "gather_wire_bytes_in_loops", "gather_wire_bytes_total",
                "gather_overlap_frac", "gather_async_windows",
                "gather_async_windows_overlapped",
            )
        }
        assert rec["gather_overlap_frac"] > 0
        from tiny_deepspeed_tpu.telemetry.schema import validate_record
        assert validate_record(
            {"kind": "run_meta", "ts": 1.0, "gather_overlap": rec}
        ) == []

    def test_unsupported_configs_raise(self, model):
        opt = AdamW(lr=1e-3)
        with pytest.raises(ValueError, match="requires ZeRO-3"):
            Zero2(model, opt, gather_prefetch=2)
        with pytest.raises(ValueError, match="requires ZeRO-3"):
            DDP(model, opt, gather_prefetch=2)
        with pytest.raises(ValueError, match="must be >= 0"):
            Zero3(model, opt, gather_prefetch=-1)
        with pytest.raises(ValueError, match="more layers than the model"):
            Zero3(model, opt, gather_prefetch=3)  # n_layer=2
        with pytest.raises(ValueError, match="gather_prefetch >= 2"):
            Zero3(model, opt, gather_groups=2)
        with pytest.raises(ValueError, match="proper divisor"):
            Zero3(model, opt, gather_prefetch=2, gather_groups=3)
        with pytest.raises(ValueError, match="proper divisor"):
            Zero3(model, opt, gather_prefetch=2, gather_groups=8)
        with pytest.raises(ValueError, match="pure data-parallel"):
            Zero3(model, opt, tensor_parallel=2, gather_prefetch=2,
                  gather_groups=2)
        from tiny_deepspeed_tpu.models.moe import MoEConfig, MoEGPT
        moe = MoEGPT(MoEConfig(
            block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
            n_expert=2, compute_dtype=jnp.float32,
        ))
        with pytest.raises(ValueError, match="gather_prefetch_capable"):
            Zero3(moe, opt, gather_prefetch=2)
        mu = GPT2Model(dataclasses.replace(TINY, scan_unroll=True))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # zero3+unroll footgun notice
            with pytest.raises(ValueError, match="scan_unroll"):
                Zero3(mu, opt, gather_prefetch=2)


# ---------------------------------------------------------------------------
# composition matrix (multi-minute: each cell is its own engine compile)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestGatherPrefetchCompositions:
    def test_hier_2hop_gather_parity(self, model, qmodel, fp32_base,
                                     fp8_base):
        """gather_groups=m: the 2-hop schedule (resting precision intra-
        group, dequant once, compute dtype inter-group) changes only
        where bytes move, not values — and with rest == cd the staged
        gather moves the SAME ring bytes as the flat one, which the
        corrected comm_report hier formula tracks."""
        hier_f = exec_curve(model, 8, keep_text=True, gather_prefetch=2,
                            gather_groups=2)
        # without quantization both hops are lossless compute dtype
        assert _rel(fp32_base["losses"][:8], hier_f["losses"]) < 1e-4
        hier_q = exec_curve(qmodel, 8, gather_prefetch=2, gather_groups=2)
        assert _rel(fp8_base["losses"][:8], hier_q["losses"]) < 0.05
        # the 2-hop program's explicit gathers live in the scan loops too
        led = collective_ledger(hier_f["text"])
        assert led["wire_bytes_in_loops"].get("all-gather", 0) > 0
        predicted = comm_report(hier_f["eng"])["zero3_layer_gather_bytes"]
        measured = sum(
            led["wire_bytes"].get(op, 0.0) for op in _GATHERING
        )
        assert abs(measured - predicted) <= 0.10 * predicted, \
            (measured, predicted)

    def test_telemetry_capture_compiled_round_trip(self, fp32_pf):
        """The full capture_compiled path (its own AOT compile): gauge
        set, gather_overlap record assembled, comm model labeled."""
        telem = Telemetry()
        out = telem.capture_compiled(
            fp32_pf["state"], fp32_pf["batch"], engine=fp32_pf["eng"])
        assert telem.gauge("gather_overlap_frac") > 0
        assert out["gather_overlap"]["gather_wire_bytes_in_loops"] > 0
        assert out["comm_model"]["gather_prefetch"] == 2

    def test_eval_loss_unchanged_semantics(self, fp32_pf):
        v = float(fp32_pf["eng"].eval_loss(
            fp32_pf["state"], make_batch(7)))
        assert np.isfinite(v)

    def test_single_device_inert_with_warning(self, model):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            eng = Zero3(model, AdamW(lr=1e-3),
                        mesh=make_mesh(devices=[jax.devices()[0]]),
                        gather_prefetch=2)
        assert any("inert" in str(x.message) for x in w)
        assert not eng._gather_prefetch_active
        state = eng.init(jax.random.PRNGKey(0))
        state, loss = eng.step(state, make_batch(b=4))
        assert np.isfinite(float(loss))

    def test_accum_composes(self, model):
        base = exec_curve(model, 6, accum_steps=2)["losses"]
        pf = exec_curve(model, 6, accum_steps=2,
                        gather_prefetch=2)["losses"]
        assert _rel(base, pf) < 1e-4

    def test_dropout_composes(self):
        """Per-layer dropout keys cross the prefetched scan's custom_vjp
        bitcast to f32 and are re-sliced per layer — the SAME masks as
        the on-demand scan, so the curves match to reassociation level."""
        md = GPT2Model(dataclasses.replace(TINY, dropout=0.1))
        base = exec_curve(md, 6)["losses"]
        pf = exec_curve(md, 6, gather_prefetch=2)["losses"]
        assert _rel(base, pf) < 1e-4

    def test_dynamic_loss_scale_and_clip_compose(self, model):
        run = exec_curve(model, 6, gather_prefetch=2,
                         loss_scale="dynamic", grad_clip=1.0)
        assert run["losses"][-1] < run["losses"][0]
        assert all(np.isfinite(x) for x in run["losses"])

    def test_llama_family_composes(self):
        m = LlamaModel(LlamaConfig(
            block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
            compute_dtype=jnp.float32,
        ))
        base = exec_curve(m, 4)["losses"]
        pf = exec_curve(m, 4, gather_prefetch=2)["losses"]
        assert _rel(base, pf) < 1e-4
        assert pf[-1] < pf[0]


# ---------------------------------------------------------------------------
# the wire model
# ---------------------------------------------------------------------------

class TestGatherWireModel:
    def test_flat_vs_hier_formula(self):
        # flat: resting payload * (n-1)/n
        assert qcomm.modeled_gather_wire_bytes(800, 1600, 8) == \
            pytest.approx(800 * 7 / 8)
        # 2-hop n=8 inner=2: hop1 rest*(inner-1)/n + hop2 cd*(g-1)/g
        assert qcomm.modeled_gather_wire_bytes(800, 1600, 8, inner=2) == \
            pytest.approx(800 * 1 / 8 + 1600 * 3 / 4)
        # rest == cd: staging an all-gather in two hops moves the same
        # bytes as the flat one (the CPU-ledger-verified identity)
        assert qcomm.modeled_gather_wire_bytes(1600, 1600, 8, inner=2) == \
            pytest.approx(qcomm.modeled_gather_wire_bytes(1600, 1600, 8))
        # degenerate groups fall back to flat; 1 device moves nothing
        assert qcomm.modeled_gather_wire_bytes(800, 1600, 8, inner=8) == \
            pytest.approx(800 * 7 / 8)
        assert qcomm.modeled_gather_wire_bytes(800, 1600, 1) == 0.0


# ---------------------------------------------------------------------------
# round-8 satellites
# ---------------------------------------------------------------------------

class TestSatellites:
    def test_grad_buckets_gather_quant_refusal_lifted(self):
        """grad_buckets x gather_quant used to refuse (e4m3 cotangents
        would have reached the tap collectives); the scheduler composes
        them now — the composed backward accumulates dW in f32 before
        each bucket release, so the combination lowers instead of
        raising, and it trains."""
        q = GPT2Model(TINY_Q)
        eng = DDP(q, AdamW(lr=1e-3), grad_buckets=2)
        assert eng._lowering == "composed"
        state = eng.init(jax.random.PRNGKey(0))
        state, loss = eng.step(state, make_batch())
        assert np.isfinite(float(loss))

    def test_offload_prefetch_validated_not_clamped(self, model):
        """offload_prefetch used to silently clamp via max(2, ...): now
        values < 1 raise, and 1 is honored as 'no double buffer' (serial
        per-leaf streaming)."""
        opt = AdamW(lr=1e-3)
        with pytest.raises(ValueError, match="offload_prefetch must be"):
            Zero2(model, opt, offload_prefetch=0)
        with pytest.raises(ValueError, match="offload_prefetch must be"):
            Zero2(model, opt, offload_prefetch=-3)
        eng = Zero2(model, opt, offload_prefetch=1)
        assert eng.offload_prefetch == 1  # no clamp to 2
        eng = Zero2(model, opt, offload_prefetch=4)
        assert eng.offload_prefetch == 4
