# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Repo hygiene gates: build artifacts must never be tracked.

A committed `__pycache__` .pyc once rode along with a PR; these tests make
that class of regression fail CI instead of relying on reviewer eyes.
Skipped (not failed) when the checkout has no git metadata (sdist/tarball
installs)."""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tracked-path fragments that are always build artifacts, never source
_ARTIFACT_MARKERS = ("__pycache__",)
_ARTIFACT_SUFFIXES = (".pyc", ".pyo", ".pyd")


def _tracked_files():
    if not os.path.isdir(os.path.join(REPO, ".git")):
        pytest.skip("not a git checkout (no .git directory)")
    try:
        out = subprocess.run(
            ["git", "ls-files"], cwd=REPO, capture_output=True,
            text=True, timeout=30,
        )
    except FileNotFoundError:
        pytest.skip("git unavailable")
    if out.returncode != 0:
        pytest.skip(f"git ls-files failed: {out.stderr[:200]}")
    return out.stdout.splitlines()


def test_no_tracked_bytecode_artifacts():
    bad = [
        p for p in _tracked_files()
        if any(m in p for m in _ARTIFACT_MARKERS)
        or p.endswith(_ARTIFACT_SUFFIXES)
    ]
    assert not bad, (
        f"tracked build artifacts: {bad} — `git rm --cached` them; "
        ".gitignore already excludes __pycache__/ and *.pyc"
    )


def test_gitignore_covers_bytecode():
    """The .gitignore entries the tracked-artifact gate relies on must
    stay present (removing them re-opens the accidental-add path)."""
    with open(os.path.join(REPO, ".gitignore")) as f:
        lines = {ln.strip() for ln in f}
    assert "__pycache__/" in lines
    assert "*.pyc" in lines
    assert "*.so" in lines


# the ONE shared object this repo may ever carry: the native dataloader
# builds libtds_dataloader.so next to its source on first use
# (data/loader.py), and some checkouts have shipped the prebuilt binary.
# Nothing else compiled belongs in the tree.
_ALLOWED_SO = {"tiny_deepspeed_tpu/native/libtds_dataloader.so"}


def test_no_new_tracked_shared_objects():
    """Pin that no build artifact beyond the allowlisted native-loader
    binary ever gets tracked: .so files are machine-specific build
    outputs (g++ rebuilds the loader from dataloader.cpp on first use),
    and a second one appearing in `git ls-files` means someone committed
    their local build."""
    bad = [
        p for p in _tracked_files()
        if p.endswith((".so", ".dylib", ".a", ".o"))
        and p not in _ALLOWED_SO
    ]
    assert not bad, (
        f"tracked compiled artifacts beyond the allowlist: {bad} — "
        f"`git rm --cached` them (.gitignore already excludes *.so; "
        f"only {sorted(_ALLOWED_SO)} is tolerated for historical "
        f"checkouts)"
    )


def _load_tier1_times():
    # the session gate's loader is the one under test — share it rather
    # than keeping a second copy of the importlib boilerplate in sync
    from conftest import _tier1_times
    return _tier1_times()


def test_tier1_budget_check_predicate():
    """The shared budget predicate (scripts/tier1_times.budget_check):
    CLI --budget exit codes and the conftest session gate both ride it,
    so its pass/fail boundary is pinned here — including the headroom
    report and the thin-headroom WARNING (a pass with <60s to spare on
    this 2-vCPU box is one noisy neighbor away from truncation)."""
    m = _load_tier1_times()
    ok, msg = m.budget_check(100.0, 870.0)
    assert ok and "within budget" in msg
    assert "headroom 770.0s" in msg and "WARNING" not in msg
    ok, msg = m.budget_check(820.0, 870.0)  # passes, but thin
    assert ok and "WARNING" in msg and "headroom 50.0s" in msg
    assert "slow" in msg  # the warning names the remedy
    ok, msg = m.budget_check(871.0, 870.0)
    assert not ok and "EXCEEDED" in msg and "slow" in msg
    # the CLI surfaces it as exit code 1 on a parsed log
    durations = [(500.0, "call", "tests/test_a.py::t"),
                 (400.0, "call", "tests/test_b.py::t")]
    assert m.report(durations, budget=870.0) == 1
    assert m.report(durations, budget=1000.0) == 0


def test_tier1_budget_gate_is_wired_into_conftest():
    """The session gate must stay wired: tests/conftest.py imports the
    budget predicate from scripts/tier1_times.py and applies it at
    sessionfinish — removing the hook would silently re-open the
    truncation failure mode the budget exists to catch."""
    with open(os.path.join(REPO, "tests", "conftest.py")) as f:
        text = f.read()
    assert "def pytest_sessionfinish" in text
    assert "budget_check" in text
    assert "tier1_times" in text


def test_gauge_names_documented_in_schema():
    """Name-drift guard: every telemetry gauge registered by a literal
    `.gauge("name", ...)` call anywhere in the package/scripts/bench must
    be documented in telemetry/schema.GAUGES — dashboards key on these
    names, so an undocumented (or renamed-in-code-only) gauge silently
    desynchronizes them from the code."""
    import re

    from tiny_deepspeed_tpu.telemetry import schema

    pat = re.compile(r"""\.gauge\(\s*['"]([A-Za-z0-9_]+)['"]""")
    used = {}
    roots = [
        os.path.join(REPO, "tiny_deepspeed_tpu"),
        os.path.join(REPO, "scripts"),
        os.path.join(REPO, "examples"),
        os.path.join(REPO, "bench.py"),
    ]
    for root in roots:
        files = [root] if root.endswith(".py") else [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(root) for f in fs if f.endswith(".py")
        ]
        for path in files:
            with open(path) as f:
                for name in pat.findall(f.read()):
                    used.setdefault(name, os.path.relpath(path, REPO))
    assert used, "no gauge call sites found — the grep pattern rotted"
    undocumented = {n: p for n, p in used.items() if n not in schema.GAUGES}
    assert not undocumented, (
        f"gauge names registered in code but not documented in "
        f"telemetry/schema.GAUGES: {undocumented} — add them there "
        "(one line each) so the metrics surface stays self-describing"
    )


def test_serving_robustness_schema_v5_names():
    """The serving fault surface is part of the schema contract: the
    v5 gauges must stay documented AND registered by the engine (a
    rename on either side desynchronizes dashboards), and the
    terminal-status request-record fields must stay validatable —
    `report_run.py --check` hard-fails on records carrying them
    otherwise."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 5
    v5_gauges = {"serve_shed", "serve_expired", "serve_quarantined",
                 "serve_restarts"}
    assert v5_gauges <= set(schema.GAUGES), (
        v5_gauges - set(schema.GAUGES))
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "serving", "engine.py")) as f:
        engine_src = f.read()
    for g in sorted(v5_gauges):
        assert f'"{g}"' in engine_src, (
            f"gauge {g} documented in schema but no longer registered "
            "by serving/engine.py"
        )
    for field, ty in (("status", str), ("finish", str),
                      ("deadline_s", (int, float)), ("slot", int)):
        assert field in schema.META_FIELDS
    # a representative terminal record of each status validates
    for status, finish in (("ok", "length"), ("shed", "shed:queue"),
                           ("expired", "deadline"),
                           ("failed", "nonfinite_logits")):
        errs = schema.validate_record({
            "kind": "request", "ts": 0.0, "request_id": 1,
            "prompt_tokens": 4, "new_tokens": 2, "preemptions": 0,
            "status": status, "finish": finish,
        })
        assert not errs, (status, errs)


def test_serving_observability_schema_v6_names():
    """Schema-v6 drift guard (serving observability): the `tick` record
    kind with its full field set, the request lifecycle/attribution
    fields, and the ICI-vs-DCN gauge must stay documented AND wired —
    `report_run.py --check` hard-fails any sidecar carrying them
    otherwise, and the dashboards key on these names."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 6
    assert "tick" in schema.META_KINDS
    assert "dcn_wire_bytes" in schema.GAUGES
    # a representative tick record of each emission class validates
    for emit in ("event", "sample"):
        errs = schema.validate_record({
            "kind": "tick", "ts": 0.0, "tick": 3, "t_s": 1.25,
            "wall_s": 0.01, "sched_s": 0.001, "prefill_s": 0.004,
            "decode_s": 0.004, "fetch_s": 0.001, "occupancy": 0.5,
            "pool_util": 0.25, "queue_depth": 1, "admitted": 1,
            "evicted": 0, "preempted": 0, "shed": 0, "expired": 0,
            "quarantined": 0, "restarted": 0, "produced": 2,
            "emit": emit,
        })
        assert not errs, (emit, errs)
    # a v6 request record (events + latency-component partition)
    errs = schema.validate_record({
        "kind": "request", "ts": 0.0, "request_id": 1,
        "prompt_tokens": 4, "new_tokens": 2, "preemptions": 1,
        "status": "ok", "finish": "length", "slot": 0,
        "lat_s": 0.1, "comp_queue_s": 0.02, "comp_prefill_s": 0.01,
        "comp_decode_s": 0.05, "comp_preempt_s": 0.02,
        "comp_restart_s": 0.0,
        "events": [["submitted", 0.0], ["admitted", 0.02, 0],
                   ["terminal:ok", 0.1, 0]],
    })
    assert not errs, errs
    # the engine still registers the tick-record emission and the
    # attribution fields it promises
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "serving", "engine.py")) as f:
        engine_src = f.read()
    for name in ('kind="tick"', "comp_queue_s", "comp_restart_s",
                 "serve_restart", "serve_quarantine",
                 "serve_shed_burst", "serve_recover"):
        assert name in engine_src, f"{name} gone from serving/engine.py"


def test_serving_spec_schema_v7_names():
    """Schema-v7 drift guard (speculative decoding): the spec gauges
    must stay documented AND registered by the engine, the draft_s
    tick field and the per-request spec_proposed/spec_accepted fields
    must stay validatable, and the ServeConfig knobs the docs/bench
    name must still exist — `report_run.py --check` hard-fails any
    spec sidecar otherwise, and BENCH_SPEC keys its fingerprint on the
    knob names."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 7
    v7_gauges = {"serve_spec_accept_rate", "serve_spec_tokens_per_tick"}
    assert v7_gauges <= set(schema.GAUGES), (
        v7_gauges - set(schema.GAUGES))
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "serving", "engine.py")) as f:
        engine_src = f.read()
    for g in sorted(v7_gauges):
        assert f'"{g}"' in engine_src, (
            f"gauge {g} documented in schema but no longer registered "
            "by serving/engine.py"
        )
    # the spec knobs the bench fingerprint and docs name
    for knob in ("spec_draft", "spec_k"):
        assert knob in engine_src, f"ServeConfig.{knob} gone"
    # a spec-enabled tick record (draft_s) and request record validate
    errs = schema.validate_record({
        "kind": "tick", "ts": 0.0, "tick": 3, "t_s": 1.25,
        "wall_s": 0.01, "sched_s": 0.001, "draft_s": 0.002,
        "prefill_s": 0.0, "decode_s": 0.004, "fetch_s": 0.001,
        "occupancy": 0.5, "pool_util": 0.25, "queue_depth": 0,
        "admitted": 0, "evicted": 0, "preempted": 0, "shed": 0,
        "expired": 0, "quarantined": 0, "restarted": 0, "produced": 7,
        "emit": "sample",
    })
    assert not errs, errs
    errs = schema.validate_record({
        "kind": "request", "ts": 0.0, "request_id": 1,
        "prompt_tokens": 4, "new_tokens": 8, "preemptions": 0,
        "status": "ok", "finish": "length",
        "spec_proposed": 12, "spec_accepted": 9,
    })
    assert not errs, errs


def test_fleet_schema_v8_names():
    """Schema-v8 drift guard (fleet serving): the router gauges must
    stay documented AND registered by fleet/router.py, the engine must
    stamp replica_id / kv_migration_* on its records, the chaos
    harness must keep the engine_kill kind the failover tests key on —
    and v8 records must validate, else `report_run.py --check`
    hard-fails every fleet sidecar."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 8
    v8_gauges = {"fleet_dispatch", "fleet_failover",
                 "fleet_replicas_live"}
    assert v8_gauges <= set(schema.GAUGES), (
        v8_gauges - set(schema.GAUGES))
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "fleet", "router.py")) as f:
        router_src = f.read()
    for g in sorted(v8_gauges):
        assert f'"{g}"' in router_src, (
            f"gauge {g} documented in schema but no longer registered "
            "by fleet/router.py"
        )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "serving", "engine.py")) as f:
        engine_src = f.read()
    for name in ("replica_id", "kv_migration_bytes",
                 "kv_migration_link"):
        assert name in schema.META_FIELDS, name
        assert name in engine_src, (
            f"{name} gone from serving/engine.py record stamping"
        )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "resilience", "chaos.py")) as f:
        chaos_src = f.read()
    assert "engine_kill" in chaos_src, (
        "chaos engine_kill kind gone — the fleet failover A/B and "
        "tests key on it"
    )
    # a fleet request record (replica + migration attribution) and a
    # replica-stamped tick record validate
    errs = schema.validate_record({
        "kind": "request", "ts": 0.0, "request_id": 1,
        "prompt_tokens": 4, "new_tokens": 8, "preemptions": 0,
        "status": "ok", "finish": "length", "replica_id": 1,
        "kv_migration_bytes": 7168, "kv_migration_link": "dcn",
    })
    assert not errs, errs
    errs = schema.validate_record({
        "kind": "tick", "ts": 0.0, "tick": 3, "t_s": 1.25,
        "wall_s": 0.01, "sched_s": 0.001, "prefill_s": 0.004,
        "decode_s": 0.004, "fetch_s": 0.001, "occupancy": 0.5,
        "pool_util": 0.25, "queue_depth": 1, "admitted": 1,
        "evicted": 0, "preempted": 0, "shed": 0, "expired": 0,
        "quarantined": 0, "restarted": 0, "produced": 2,
        "replica_id": 0, "emit": "event",
    })
    assert not errs, errs
    # the failover fault record the router writes
    errs = schema.validate_record({
        "kind": "fault", "ts": 0.0, "fault": "fleet_failover",
        "at_step": 4, "replica_id": 0,
        "action": "replica 0 died; journal replayed onto replica 1",
    })
    assert not errs, errs


def test_prefix_tenancy_schema_v9_names():
    """Schema-v9 drift guard (shared-prefix KV reuse + multi-tenant
    serving): the serve_prefix_* / serve_tenants_active gauges must
    stay documented AND registered by the engine, the request-record
    tenant/prefix fields must stay validatable, the ServeConfig knobs
    the bench/docs name must exist, and the chaos tenant_flood kind the
    isolation pin keys on must survive — `report_run.py --check`
    hard-fails any v9 sidecar otherwise."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 9
    v9_gauges = {"serve_prefix_hit_rate", "serve_prefix_blocks_aliased",
                 "serve_prefix_tokens_avoided",
                 "serve_prefix_cached_blocks",
                 "serve_prefix_pool_saved_bytes", "serve_tenants_active"}
    assert v9_gauges <= set(schema.GAUGES), (
        v9_gauges - set(schema.GAUGES))
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "serving", "engine.py")) as f:
        engine_src = f.read()
    for g in sorted(v9_gauges):
        assert f'"{g}"' in engine_src, (
            f"gauge {g} documented in schema but no longer registered "
            "by serving/engine.py"
        )
    # the knobs serve_bench/BENCH_PREFIX and the docs name
    for knob in ("prefix_cache", "tenants"):
        assert knob in engine_src, f"ServeConfig.{knob} gone"
    for field in ("tenant", "prefix_blocks", "prefix_tokens"):
        assert field in schema.META_FIELDS, field
        assert field in engine_src, (
            f"{field} gone from serving/engine.py record stamping"
        )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "resilience", "chaos.py")) as f:
        chaos_src = f.read()
    assert "tenant_flood" in chaos_src, (
        "chaos tenant_flood kind gone — the multi-tenant isolation "
        "pin and serve_bench flood A/B key on it"
    )
    # a v9 request record (tenant + prefix attribution) validates
    errs = schema.validate_record({
        "kind": "request", "ts": 0.0, "request_id": 1,
        "prompt_tokens": 72, "new_tokens": 8, "preemptions": 0,
        "status": "ok", "finish": "length", "tenant": "pro",
        "prefix_blocks": 4, "prefix_tokens": 64,
    })
    assert not errs, errs
    # tenant_queue_watermark shed reason reaches records unchanged
    errs = schema.validate_record({
        "kind": "request", "ts": 0.0, "request_id": 2,
        "prompt_tokens": 8, "new_tokens": 0, "preemptions": 0,
        "status": "shed", "finish": "shed:tenant_queue_watermark",
        "tenant": "abuser",
    })
    assert not errs, errs


def test_no_scan_tap_custom_vjp_outside_schedule():
    """Scheduler-consolidation guard (the PR-15 tentpole): the four-way
    custom_vjp scan-tap drift this repo once carried (bucket taps,
    prefetch scan, health probe, quantized schedule — each with its own
    pairwise refusals) was unified into parallel/schedule.py.  No NEW
    `jax.custom_vjp` scan-tap may appear under parallel/ or models/
    outside schedule.py — per-layer in-scan work must be declared as a
    scheduler SLOT instead, so the drift cannot regrow."""
    import ast

    # ring_attention's custom_vjp is an ATTENTION-KERNEL vjp (per-chunk
    # softmax merge), not a scan tap riding the block scan — it predates
    # the scheduler and schedules nothing
    allow = {"parallel/ring_attention.py"}
    offenders = {}
    for sub in ("parallel", "models"):
        root = os.path.join(REPO, "tiny_deepspeed_tpu", sub)
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".py") or fn == "schedule.py":
                continue
            rel = f"{sub}/{fn}"
            if rel in allow:
                continue
            with open(os.path.join(root, fn)) as f:
                tree = ast.parse(f.read())
            hits = [
                node.lineno for node in ast.walk(tree)
                if isinstance(node, ast.Attribute)
                and node.attr == "custom_vjp"
            ]
            if hits:
                offenders[rel] = hits
    assert not offenders, (
        f"jax.custom_vjp scan-tap outside parallel/schedule.py: "
        f"{offenders} — declare the per-layer work as a scheduler slot "
        "(GatherSlot/GradSlot/ProbeSlot) in parallel/schedule.py instead "
        "of growing a fifth bespoke tap"
    )


def test_scheduler_schema_v11_names():
    """Schema-v11 drift guard (the in-scan collective scheduler): the
    per-slot overlap gauges + the hpZ acceptance gauge must stay
    documented AND registered by telemetry/registry.capture_compiled,
    and the ledger must keep the loop-resident per-op group split the
    hpZ pin reads."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 11
    v11_gauges = {"sched_gather_overlap_frac", "sched_grad_overlap_frac",
                  "hpz_dcn_wire_bytes"}
    assert v11_gauges <= set(schema.GAUGES), (
        v11_gauges - set(schema.GAUGES))
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "telemetry", "registry.py")) as f:
        reg_src = f.read()
    for g in sorted(v11_gauges):
        assert f'"{g}"' in reg_src, (
            f"gauge {g} documented in schema but no longer registered "
            "by telemetry/registry.py capture_compiled"
        )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "utils", "hlo_comm.py")) as f:
        hlo_src = f.read()
    for name in ("wire_bytes_by_op_groups_in_loops",
                 "gather_link_split_in_loops"):
        assert name in hlo_src, (
            f"{name} gone from utils/hlo_comm.py — the hpZ in-scan DCN "
            "pin reads it"
        )


def test_hlo_cost_schema_v12_names():
    """Schema-v12 drift guard (the HLO cost ledger): the roofline gauges
    must stay documented AND registered by telemetry/registry
    capture_compiled, and utils/hlo_cost.py must keep the entry points
    the reports and bench read."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 12
    v12_gauges = {"hlo_flops", "hlo_hbm_bytes", "step_mfu_hlo",
                  "arithmetic_intensity"}
    assert v12_gauges <= set(schema.GAUGES), (
        v12_gauges - set(schema.GAUGES))
    assert schema.META_FIELDS.get("hlo_cost") is dict
    assert "compute_spans" in schema.META_FIELDS
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "telemetry", "registry.py")) as f:
        reg_src = f.read()
    for g in sorted(v12_gauges):
        assert f'"{g}"' in reg_src, (
            f"gauge {g} documented in schema but no longer registered "
            "by telemetry/registry.py capture_compiled"
        )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "utils", "hlo_cost.py")) as f:
        cost_src = f.read()
    for name in ("cost_ledger", "cost_summary", "roofline_verdict",
                 "peak_flops_per_chip"):
        assert name in cost_src, (
            f"{name} gone from utils/hlo_cost.py — reports, bench and "
            "the registry read it"
        )


def test_wire_agenda_schema_v13_names():
    """Schema-v13 drift guard (the wire-agenda close-out): the quantized
    tail / hpZ rebuild gauges must stay documented AND registered by
    telemetry/registry.capture_compiled, utils/hlo_comm.py must keep
    the exact-group isolation helper the rebuild pin reads, and the
    scheduler must keep the "auto" sizing + plan round-trip entry
    points bench and the tuner consume."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 13
    v13_gauges = {"zero3_tail_wire_bytes", "hpz_rebuild_dcn_bytes"}
    assert v13_gauges <= set(schema.GAUGES), (
        v13_gauges - set(schema.GAUGES))
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "telemetry", "registry.py")) as f:
        reg_src = f.read()
    for g in sorted(v13_gauges):
        assert f'"{g}"' in reg_src, (
            f"gauge {g} documented in schema but no longer registered "
            "by telemetry/registry.py capture_compiled"
        )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "utils", "hlo_comm.py")) as f:
        hlo_src = f.read()
    assert "group_wire_outside_loops" in hlo_src, (
        "group_wire_outside_loops gone from utils/hlo_comm.py — the "
        "hpZ rebuild pin and the registry gauge read it"
    )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "parallel", "schedule.py")) as f:
        sched_src = f.read()
    for name in ("auto_comm_plan", "comm_plan_engine_kwargs",
                 "COMM_PLAN_KEYS"):
        assert name in sched_src, (
            f"{name} gone from parallel/schedule.py — bench's comm "
            "phase and the AOT plan round-trip consume it"
        )


def test_live_slo_schema_v15_names():
    """Schema-v15 drift guard (live observability plane): the `slo`
    record kind and the cross-engine tracing fields must stay
    documented, the engine must keep stamping trace_id / comp_migrate_s
    and arming the slo_fast_burn flight, the registry must keep
    label-qualifying gauge keys through telemetry/live.gauge_key, and
    serve_bench must keep the --slo / --live-port surfaces the docs
    name — `report_run.py --check` hard-fails any v15 sidecar
    otherwise."""
    from tiny_deepspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 15
    assert "slo" in schema.META_KINDS
    for field in ("trace_id", "comp_migrate_s", "windows", "tenants",
                  "attainment", "alerts"):
        assert field in schema.META_FIELDS, field
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "serving", "engine.py")) as f:
        engine_src = f.read()
    for name in ("trace_id", "slo_fast_burn", "attach_slo",
                 "attach_live"):
        assert name in engine_src, (
            f"{name} gone from serving/engine.py — the live plane and "
            "cross-engine tracing key on it"
        )
    assert "comp_migrate_s" in engine_src, (
        "comp_migrate_s gone from serving/engine.py record stamping — "
        "the disagg tail attribution keys on it"
    )
    with open(os.path.join(
            REPO, "tiny_deepspeed_tpu", "telemetry", "registry.py")) as f:
        reg_src = f.read()
    assert "gauge_key" in reg_src, (
        "registry gauges no longer label-qualified via "
        "telemetry/live.gauge_key — fleet replicas would regress to "
        "last-writer-wins shared gauges"
    )
    with open(os.path.join(REPO, "scripts", "serve_bench.py")) as f:
        bench_src = f.read()
    for flag in ("--slo", "--live-port"):
        assert flag in bench_src, (
            f"serve_bench {flag} gone — README's observability recipe "
            "and the live smoke test drive it"
        )
    # a v15 slo record (the SLOTracker.record shape) validates
    errs = schema.validate_record({
        "kind": "slo", "ts": 0.0, "windows": {"s": [30.0, 300.0]},
        "tenants": {"_default": {
            "objective": {"target": 0.99, "ttft_s": None,
                          "latency_s": None},
            "requests": 10, "good": 9, "attainment": 0.9,
            "budget_spent_frac": 1.0,
            "burn": {"30s": 10.0, "300s": 2.0}}},
        "attainment": 0.9, "at_step": 12,
        "alerts": [{"tenant": "_default", "kind": "fast_burn",
                    "burn": 10.0, "window_s": 30.0, "threshold": 14.0,
                    "t": 1.5}],
    })
    assert not errs, errs
    # a v15 request record: trace_id correlation + the migrate
    # component joining the latency partition
    errs = schema.validate_record({
        "kind": "request", "ts": 0.0, "request_id": 1,
        "prompt_tokens": 8, "new_tokens": 4, "preemptions": 0,
        "status": "ok", "finish": "length", "lat_s": 0.5,
        "comp_queue_s": 0.1, "comp_prefill_s": 0.1,
        "comp_decode_s": 0.1, "comp_preempt_s": 0.0,
        "comp_restart_s": 0.0, "comp_migrate_s": 0.2,
        "trace_id": "t000001", "replica_id": 1,
        "events": [["submitted", 0.0], ["exported", 0.1, 0, 0],
                   ["imported", 0.2, 1, 1], ["terminal:ok", 0.5, 1]],
    })
    assert not errs, errs
    # labeled gauge keys in a telemetry_summary validate as plain dict
    # entries (the key carries the label, the schema names the base)
    errs = schema.validate_record({
        "kind": "telemetry_summary", "ts": 0.0,
        "gauges": {"serve_queue_depth{replica=0}": 1.0,
                   "serve_queue_depth{replica=1}": 0.0},
        "counters": {}, "histograms": {},
    })
    assert not errs, errs


def test_perf_diff_check_committed_trajectory():
    """CI wiring for the perf regression sentinel: `perf_diff --check`
    must run green against the committed BENCH_*.json trajectory.  A
    nonzero exit here means either a real cross-round regression was
    committed or the sentinel itself broke — both block the PR."""
    import glob
    import sys

    rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
    assert rounds, "no committed BENCH_*.json rounds to gate on"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         "--check", *rounds],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert proc.returncode == 0, (
        f"perf_diff --check flagged the committed trajectory:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
