# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""The composable in-scan collective scheduler (parallel/schedule.py).

Pins the tentpole contract end to end:

  * build_schedule's LOWERING TABLE — every legacy single-feature knob
    routes to its pre-scheduler lowering (probe / bucket / quant_mono /
    prefetch) and every real composition routes to the composed machine
    (including the lifted refusals: ZeRO-3 x grad slots via the implicit
    on-demand gather, grad_buckets x gather_quant, health x everything).
  * the ONE refusal path: ScheduleConflictError names the conflicting
    SLOT for genuinely inexpressible requests.
  * single-feature byte-identity, fresh-subprocess: the scheduler
    routing is deterministic across processes — the same knobs lower to
    the same HLO bytes in a fresh interpreter (the historical half of
    the pin — scheduler-routed == pre-scheduler program — was verified
    against pre-port HLO dumps when the port landed; the off-path pins
    in test_grad_buckets / test_zero3_gather_prefetch / test_trace_flight
    anchor the other side).
  * the FULL STACK in one program: ZeRO-3 + gather_prefetch=2 +
    grad_buckets=2 + int8 grad comm + per-layer health — 20-step loss
    parity (fp32 < 1e-4, quantized < 5%) and the overlap ledger showing
    loop-resident gather AND grad wire on the merged program.
  * hpZ secondary weight partitioning on the emulated 2-slice mesh:
    in-scan gather dcn_wire_bytes == 0 (utils/hlo_comm.
    gather_link_split_in_loops), the hpz_dcn_wire_bytes gauge, and the
    per-slice replica priced as the bwd residual stash.

Budget note (zero-sum tier-1 rule): every multi-engine trace here is
slow-marked from the start; the quick tier is build_schedule unit logic
only (no compiles) plus the budget-gate headroom assertion — the cheap
composed-wiring smoke lives in test_trace_flight (one DDP compile,
shared with the lifted-refusal pin).
"""

import os
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import (
    AdamW, DDP, GPTConfig, GPT2Model, SingleDevice, Telemetry, Zero2,
    Zero3,
)
from tiny_deepspeed_tpu.parallel import schedule as S

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)
GRAN2 = {i: i // 4 for i in range(8)}  # emulated 2-slice mesh (8 cpu dev)


def make_batch(seed=1, b=8, t=32, vocab=128):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return (jax.random.randint(k1, (b, t), 0, vocab),
            jax.random.randint(k2, (b, t), 0, vocab))


@pytest.fixture(scope="module")
def model():
    return GPT2Model(TINY)


def run_curve(eng, steps=20, seed=1):
    state = eng.init(jax.random.PRNGKey(0))
    batch = make_batch(seed)
    losses = []
    for _ in range(steps):
        state, loss = eng.step(state, batch)
        losses.append(float(loss))
    return losses, state


# ---------------------------------------------------------------------------
# build_schedule: the lowering table (quick — no compiles)
# ---------------------------------------------------------------------------

def _build(model, **kw):
    args = dict(model=model, stage=0, n_shard=8,
                busy_axes=(None, None, None, None), accum_steps=1,
                scan_unroll=1)
    args.update(kw)
    return S.build_schedule(**args)


class TestLoweringTable:
    def test_plain(self, model):
        assert _build(model).lowering == "plain"

    def test_single_feature_legacy_lowerings(self, model):
        assert _build(model, telemetry_layers=True).lowering == "probe"
        assert _build(model, grad_buckets=2).lowering == "bucket"
        assert _build(model, grad_comm="int8").lowering == "quant_mono"
        assert _build(model, stage=3,
                      gather_prefetch=2).lowering == "prefetch"
        # 2-hop variants stay on their legacy lowerings too
        assert _build(model, grad_comm="fp8",
                      grad_comm_groups=2).lowering == "quant_mono"
        assert _build(model, stage=3, gather_prefetch=2,
                      gather_groups=2).lowering == "prefetch"

    def test_compositions_route_to_composed(self, model):
        assert _build(model, grad_buckets=2,
                      telemetry_layers=True).lowering == "composed"
        assert _build(model, grad_comm="int8",
                      telemetry_layers=True).lowering == "composed"
        assert _build(model, stage=3, gather_prefetch=2,
                      telemetry_layers=True).lowering == "composed"
        sched = _build(model, stage=3, gather_prefetch=2,
                       grad_buckets=2, grad_comm="int8",
                       telemetry_layers=True)
        assert sched.lowering == "composed"
        assert "gather_prefetch=2" in sched.describe()
        assert "grad_buckets=2" in sched.describe()
        assert "health" in sched.describe()

    def test_zero3_grad_slot_gets_implicit_gather(self, model):
        """The lifted 'stages 0-2' refusal: ZeRO-3 + a grad slot
        declares the on-demand gather slot implicitly and composes."""
        for kw in ({"grad_comm": "int8"}, {"grad_buckets": 2}):
            sched = _build(model, stage=3, **kw)
            assert sched.lowering == "composed"
            assert sched.gather is not None
            assert sched.gather.prefetch == 1

    def test_gather_quant_buckets_forces_composed(self):
        """The lifted grad_buckets x gather_quant refusal: the legacy
        tap would put e4m3 cotangents on the bucket collectives, so the
        combination routes to the composed machine instead."""
        import dataclasses
        q = GPT2Model(dataclasses.replace(TINY, gather_quant="fp8"))
        assert _build(q, grad_buckets=2).lowering == "composed"
        # monolithic quant never tapped the scan: stays legacy
        assert _build(q, grad_comm="int8").lowering == "quant_mono"

    def test_hpz_routes_to_composed(self, model):
        sched = _build(model, stage=3, hpz=True, granule_of=GRAN2)
        assert sched.lowering == "composed"
        assert sched.gather.hpz and sched.hpz_geom is not None
        intra, inter, ici, n_gran = sched.hpz_geom
        assert ici == 4 and n_gran == 2
        assert intra == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert inter == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_inert_on_one_device(self, model):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            sched = _build(model, n_shard=1, grad_buckets=2,
                           grad_comm="int8")
        assert sched.lowering == "plain"
        assert any("inert" in str(x.message) for x in w)
        # the probe survives a 1-device mesh (plain GSPMD scan)
        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            sched = _build(model, n_shard=1, grad_buckets=2,
                           telemetry_layers=True)
        assert sched.lowering == "probe"

    def test_residual_geometry(self, model):
        # legacy bucket row: [b0 | ... | bK-1 | tail]
        lay = _build(model, grad_buckets=2, grad_comm="int8")
        assert lay.residual_len == 2 * lay.layout["bucket_pad"] + \
            lay.layout["tail_pad"]
        # composed ZeRO-3 drops the tail slice (the tail reduce-scatters
        # at full precision through the differentiable gather transpose)
        z3 = _build(model, stage=3, grad_buckets=2, grad_comm="int8")
        assert z3.residual_len == 2 * z3.layout["bucket_pad"]
        # fp32 grads carry no residual at all
        assert _build(model, grad_buckets=2).residual_len == 0


class TestRefusals:
    """The ONE loud refusal path: messages name the conflicting SLOT."""

    def test_composed_accum_named(self, model):
        with pytest.raises(S.ScheduleConflictError, match="composed "
                           "schedule.*accum_steps"):
            _build(model, grad_buckets=2, telemetry_layers=True,
                   accum_steps=2)

    def test_composed_two_hop_named(self, model):
        with pytest.raises(S.ScheduleConflictError,
                           match="gather slot.*2-hop"):
            _build(model, stage=3, gather_prefetch=2, gather_groups=2,
                   telemetry_layers=True)
        # the grad side's 2-hop refusal is LIFTED: the composed release
        # threads the hierarchical codec (inner=) through its bucket and
        # tail syncs, so the combination now BUILDS on the composed
        # machine instead of refusing
        sched = _build(model, stage=3, gather_prefetch=2,
                       grad_comm="int8", grad_comm_groups=2)
        assert sched.lowering == "composed"
        assert "2-hop inner=2" in sched.describe()

    def test_moe_named_with_slot(self):
        from tiny_deepspeed_tpu.models.moe import MoEConfig, MoEGPT
        moe = MoEGPT(MoEConfig(
            block_size=32, vocab_size=128, n_layer=2, n_head=2,
            n_embd=32, n_expert=2, compute_dtype=jnp.float32,
        ))
        with pytest.raises(S.ScheduleConflictError,
                           match="grad_buckets=2.*aux-loss"):
            _build(moe, grad_buckets=2, telemetry_layers=True)

    def test_hpz_granule_validation(self, model):
        with pytest.raises(S.ScheduleConflictError,
                           match="single DCN granule"):
            S.hpz_groups({i: 0 for i in range(8)}, 8)
        with pytest.raises(S.ScheduleConflictError, match="contiguous"):
            S.hpz_groups({i: i % 2 for i in range(8)}, 8)
        with pytest.raises(S.ScheduleConflictError, match="granule map"):
            _build(model, stage=3, hpz=True, granule_of=None)

    def test_engine_surfaces_conflict(self, model):
        """The engine raises the scheduler's error, not a legacy-knob
        message."""
        with pytest.raises(S.ScheduleConflictError):
            DDP(model, AdamW(lr=1e-3), grad_buckets=2, accum_steps=2,
                telemetry=Telemetry(layers=True))


class TestSchedSpecParsing:
    def test_round_trip(self):
        kw = S.parse_sched_spec(
            "gather_prefetch=2,grad_buckets=4,grad_comm=int8,health,hpz")
        assert kw == {"gather_prefetch": 2, "grad_buckets": 4,
                      "grad_comm": "int8", "telemetry_layers": True,
                      "hpz": True}

    def test_bad_specs(self):
        with pytest.raises(ValueError, match="unknown --sched key"):
            S.parse_sched_spec("warp=9")
        with pytest.raises(ValueError, match="grad_comm must be"):
            S.parse_sched_spec("grad_comm=int4")
        with pytest.raises(ValueError, match="not 'key=value'"):
            S.parse_sched_spec("gather_prefetch")


class TestTier1Budget:
    """Satellite: the tier-1 budget gate's headroom stays asserted in
    the module whose additions are budgeted against it."""

    def test_budget_check_headroom(self):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        os.pardir, "scripts"))
        try:
            from tier1_times import (
                TIER1_BUDGET_S, TIER1_HEADROOM_WARN_S, budget_check,
            )
        finally:
            sys.path.pop(0)
        ok, msg = budget_check(100.0, 870.0)
        assert ok and "headroom 770.0s" in msg
        ok, msg = budget_check(
            TIER1_BUDGET_S - TIER1_HEADROOM_WARN_S / 2)
        assert ok and "WARNING" in msg


# ---------------------------------------------------------------------------
# heavies (slow from the start — zero-sum tier-1 budget)
# ---------------------------------------------------------------------------

_SUBPROC_HASH = r"""
import hashlib, json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from tiny_deepspeed_tpu import AdamW, DDP, GPTConfig, GPT2Model, \
    Telemetry, Zero3
cfg = GPTConfig(block_size=32, vocab_size=128, n_layer=2, n_head=2,
                n_embd=32, compute_dtype=jnp.float32)
model = GPT2Model(cfg)
k1, k2 = jax.random.split(jax.random.PRNGKey(1))
batch = (jax.random.randint(k1, (8, 32), 0, 128),
         jax.random.randint(k2, (8, 32), 0, 128))
out = {{}}
for name, mk in [
    ("bucket", lambda: DDP(model, AdamW(lr=1e-3), grad_buckets=2)),
    ("quant_mono", lambda: DDP(model, AdamW(lr=1e-3), grad_comm="int8")),
    ("prefetch", lambda: Zero3(model, AdamW(lr=1e-3), gather_prefetch=2)),
    ("probe", lambda: DDP(model, AdamW(lr=1e-3),
                          telemetry=Telemetry(layers=True))),
]:
    eng = mk()
    state = eng.init(jax.random.PRNGKey(0))
    txt = eng._step.lower(state, batch).as_text()
    out[name] = (eng._lowering, hashlib.sha256(txt.encode()).hexdigest())
print(json.dumps(out))
"""


@pytest.mark.slow
class TestSingleFeatureIdentity:
    def test_fresh_subprocess_hlo_deterministic(self, model):
        """Every legacy tap mode routed through the scheduler lowers to
        the SAME HLO bytes in a fresh interpreter as in this process —
        the scheduler's slot dicts / executor construction introduce no
        trace-order nondeterminism, so the byte-identity verified
        against the pre-port programs keeps holding across processes."""
        import hashlib
        import json as _json
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        proc = subprocess.run(
            [sys.executable, "-c", _SUBPROC_HASH.format(repo=repo)],
            capture_output=True, text=True, timeout=900, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        remote = _json.loads(proc.stdout.strip().splitlines()[-1])
        batch = make_batch(1)
        local = {}
        for name, mk in [
            ("bucket", lambda: DDP(model, AdamW(lr=1e-3),
                                   grad_buckets=2)),
            ("quant_mono", lambda: DDP(model, AdamW(lr=1e-3),
                                       grad_comm="int8")),
            ("prefetch", lambda: Zero3(model, AdamW(lr=1e-3),
                                       gather_prefetch=2)),
            ("probe", lambda: DDP(model, AdamW(lr=1e-3),
                                  telemetry=Telemetry(layers=True))),
        ]:
            eng = mk()
            state = eng.init(jax.random.PRNGKey(0))
            txt = eng._step.lower(state, batch).as_text()
            local[name] = [eng._lowering,
                           hashlib.sha256(txt.encode()).hexdigest()]
        assert local == remote

    def test_buckets_off_still_byte_identical(self, model):
        """The off-path anchor, restated here next to the scheduler: an
        unknobbed engine and grad_buckets=1 produce identical HLO (the
        scheduler adds nothing when no slot is declared)."""
        def hlo(**kw):
            eng = DDP(model, AdamW(lr=1e-3), **kw)
            state = eng.init(jax.random.PRNGKey(0))
            return eng._step.lower(state, make_batch()).as_text()
        assert hlo() == hlo(grad_buckets=1)


@pytest.mark.slow
class TestFullStackCompose:
    """Acceptance: the real DeepSpeed hot path in ONE program."""

    def test_fp32_compose_parity_and_overlap(self, model):
        from tiny_deepspeed_tpu.utils.hlo_comm import (
            collective_ledger, overlap_report,
        )
        base, _ = run_curve(Zero3(model, AdamW(lr=1e-3)))
        telem = Telemetry(layers=True)
        eng = Zero3(model, AdamW(lr=1e-3), gather_prefetch=2,
                    grad_buckets=2, telemetry=telem)
        assert eng._lowering == "composed"
        comp, state = run_curve(eng)
        assert max(abs(a - b) for a, b in zip(base, comp)) < 1e-4
        # the probe slot delivered the per-layer matrix from the SAME
        # program
        mat = telem.layer_health()
        assert mat is not None and mat.shape[0] == TINY.n_layer
        assert np.all(np.isfinite(mat))
        # merged program: loop-resident gather AND grad wire
        txt = eng._step.lower(state, make_batch()).compile().as_text()
        rep = overlap_report(txt, led=collective_ledger(txt))
        assert rep["gather_wire_bytes_in_loops"] > 0
        assert rep["reduce_wire_bytes_in_loops"] > 0

    def test_int8_compose_parity_and_residual(self, model):
        base, _ = run_curve(Zero3(model, AdamW(lr=1e-3)))
        eng = Zero3(model, AdamW(lr=1e-3), gather_prefetch=2,
                    grad_buckets=2, grad_comm="int8",
                    telemetry=Telemetry(layers=True))
        assert eng._lowering == "composed"
        comp, state = run_curve(eng)
        assert abs(comp[-1] - base[-1]) / abs(base[-1]) < 0.05
        # composed ZeRO-3 residual: per-bucket slices, no tail slice
        lay = eng._schedule.layout
        assert state.grad_residual.shape == (
            8, 2 * lay["bucket_pad"])

    def test_two_hop_grad_compose_parity(self, model):
        """The lifted grad x 2-hop refusal actually TRAINS: the
        composed release threads the hierarchical codec (inner=)
        through its bucket and tail syncs — parity vs plain ZeRO-3
        within the quantized tolerance."""
        base, _ = run_curve(Zero3(model, AdamW(lr=1e-3)))
        eng = Zero3(model, AdamW(lr=1e-3), gather_prefetch=2,
                    grad_comm="int8", grad_comm_groups=2)
        assert eng._lowering == "composed"
        assert "2-hop inner=2" in eng._schedule.describe()
        comp, _ = run_curve(eng)
        assert abs(comp[-1] - base[-1]) / abs(base[-1]) < 0.05
        assert comp[-1] < comp[0]

    def test_probe_stats_match_plain_probe_lowering(self, model):
        """Review pin: the composed probe reports the SAME LAYER_FIELDS
        numbers as the single-slot probe lowering — the local-mean-loss
        backward seeds the dact column with n^2, which the composed
        machine must normalize away (threshold-based health monitoring
        keys on absolute values)."""
        t1 = Telemetry(layers=True)
        e1 = DDP(model, AdamW(lr=1e-3), telemetry=t1)
        assert e1._lowering == "probe"
        s1 = e1.init(jax.random.PRNGKey(0))
        e1.step(s1, make_batch(5))
        m1 = t1.layer_health()
        t2 = Telemetry(layers=True)
        e2 = DDP(model, AdamW(lr=1e-3), grad_buckets=2, telemetry=t2)
        assert e2._lowering == "composed"
        s2 = e2.init(jax.random.PRNGKey(0))
        e2.step(s2, make_batch(5))
        m2 = t2.layer_health()
        np.testing.assert_allclose(m1, m2, rtol=1e-3)

    def test_zero3_replicated_tail_leaf_parity(self):
        """Review pin: a tail leaf the ZeRO-3 layout leaves REPLICATED
        at rest (dims the data axis does not divide — n_embd=36 ln_f on
        8 ranks) never crosses the differentiable gather, so the
        composed machine must psum its local cotangent explicitly; a
        miss here is silently-wrong training, not an error."""
        import dataclasses
        cfg = dataclasses.replace(TINY, n_embd=36)
        sm = GPT2Model(cfg)
        spec = Zero3(sm, AdamW(lr=1e-3))._param_spec_rest
        repl = [nm for nm in spec if not nm.startswith("h.")
                and all(a is None for a in spec[nm])]
        assert repl, "config stopped producing a replicated tail leaf"
        base, _ = run_curve(Zero3(sm, AdamW(lr=1e-3)), steps=15)
        comp, _ = run_curve(Zero3(sm, AdamW(lr=1e-3), grad_buckets=2),
                            steps=15)
        assert max(abs(a - b) for a, b in zip(base, comp)) < 1e-4

    def test_stage2_compose_probe_quant(self, model):
        """Stages 0-2 compose too (no gather slot): monolithic-style
        quant release + health probe in one program."""
        base, _ = run_curve(Zero2(model, AdamW(lr=1e-3)))
        eng = Zero2(model, AdamW(lr=1e-3), grad_comm="int8",
                    telemetry=Telemetry(layers=True))
        assert eng._lowering == "composed"
        comp, _ = run_curve(eng)
        assert abs(comp[-1] - base[-1]) / abs(base[-1]) < 0.05


@pytest.mark.slow
class TestHpz:
    """Acceptance: hpZ on the emulated 2-slice mesh — in-scan gather
    DCN bytes ~zero (ZeRO++ arXiv:2306.10209)."""

    def test_in_scan_gather_dcn_zero(self, model):
        from tiny_deepspeed_tpu.utils.hlo_comm import (
            collective_ledger, gather_link_split_in_loops,
            wire_link_split,
        )
        base, _ = run_curve(Zero3(model, AdamW(lr=1e-3)))
        eng = Zero3(model, AdamW(lr=1e-3), hpz=True,
                    hpz_granule_of=GRAN2, gather_prefetch=2)
        comp, state = run_curve(eng)
        assert max(abs(a - b) for a, b in zip(base, comp)) < 1e-4
        txt = eng._step.lower(state, make_batch()).compile().as_text()
        led = collective_ledger(txt)
        in_scan = gather_link_split_in_loops(led, GRAN2)
        assert in_scan["dcn_wire_bytes"] == 0.0
        assert in_scan["ici_wire_bytes"] > 0.0
        # the ONE top-level secondary rebuild still crosses DCN — hpZ
        # moves the cross-slice bytes out of the scan, it does not
        # pretend they vanish
        full = wire_link_split(led, GRAN2)
        assert full["dcn_wire_bytes"] > 0.0

    def test_without_hpz_in_scan_gathers_cross_dcn(self, model):
        """The counterfactual that makes the zero meaningful: plain
        prefetched ZeRO-3 on the same emulated mesh DOES move in-scan
        gather bytes across the granule boundary."""
        from tiny_deepspeed_tpu.utils.hlo_comm import (
            collective_ledger, gather_link_split_in_loops,
        )
        eng = Zero3(model, AdamW(lr=1e-3), gather_prefetch=2)
        state = eng.init(jax.random.PRNGKey(0))
        txt = eng._step.lower(state, make_batch()).compile().as_text()
        in_scan = gather_link_split_in_loops(
            collective_ledger(txt), GRAN2)
        assert in_scan["dcn_wire_bytes"] > 0.0

    def test_hpz_gauge_via_capture_compiled(self, model):
        """Schema v11: capture_compiled gauges hpz_dcn_wire_bytes (== 0
        under hpZ) and the per-slot sched overlap fractions."""
        telem = Telemetry()
        eng = Zero3(model, AdamW(lr=1e-3), hpz=True,
                    hpz_granule_of=GRAN2, telemetry=telem)
        state = eng.init(jax.random.PRNGKey(0))
        out = telem.capture_compiled(state, make_batch(),
                                     granule_of=GRAN2)
        assert telem.gauges["hpz_dcn_wire_bytes"] == 0.0
        assert "sched_gather_overlap_frac" in telem.gauges
        split = out["comm_measured"]["wire_bytes_by_link_in_scan_gather"]
        assert split["dcn_wire_bytes"] == 0.0

    def test_hpz_full_compose_weight_gathers_stay_ici(self, model):
        """hpZ under the full int8 compose: the remaining in-loop
        DCN-crossing gather wire is the quantized grad schedule's
        all-gather completion (legitimately global), strictly less than
        the weight-gather wire the no-hpz program moved across DCN."""
        from tiny_deepspeed_tpu.utils.hlo_comm import (
            collective_ledger, gather_link_split_in_loops,
        )
        def in_scan(engine):
            state = engine.init(jax.random.PRNGKey(0))
            txt = engine._step.lower(
                state, make_batch()).compile().as_text()
            return gather_link_split_in_loops(
                collective_ledger(txt), GRAN2)
        kw = dict(gather_prefetch=2, grad_buckets=2, grad_comm="int8")
        with_hpz = in_scan(Zero3(model, AdamW(lr=1e-3), hpz=True,
                                 hpz_granule_of=GRAN2, **kw))
        without = in_scan(Zero3(model, AdamW(lr=1e-3), **kw))
        assert with_hpz["dcn_wire_bytes"] < without["dcn_wire_bytes"]
        assert with_hpz["ici_wire_bytes"] > 0.0


# ---------------------------------------------------------------------------
# wire agenda (ISSUE 17): quantized tail + fp8 hpZ rebuild + "auto" sizing
# ---------------------------------------------------------------------------

class TestWireKnobValidation:
    """Quick tier: the loud refusals and spec vocabulary of the new
    codec knobs (no compiles — build_schedule / parse only)."""

    def test_tail_needs_stage3(self, model):
        with pytest.raises(ValueError, match="ZeRO-3 knob"):
            _build(model, grad_comm="int8", grad_comm_tail="int8")

    def test_tail_needs_quantized_grad_slot(self, model):
        with pytest.raises(ValueError, match="quantized grad slot"):
            _build(model, stage=3, gather_prefetch=2,
                   grad_comm_tail="int8")

    def test_hpz_comm_needs_hpz(self, model):
        with pytest.raises(ValueError, match="hpz=True"):
            _build(model, stage=3, hpz_comm="fp8",
                   granule_of=GRAN2)

    def test_bad_modes_refused(self, model):
        with pytest.raises(ValueError, match="grad_comm_tail"):
            _build(model, stage=3, grad_comm="int8",
                   grad_comm_tail="int4")
        with pytest.raises(ValueError, match="hpz_comm"):
            _build(model, stage=3, hpz=True, granule_of=GRAN2,
                   hpz_comm="int4")

    def test_describe_names_the_codecs(self, model):
        sched = _build(model, stage=3, grad_comm="int8",
                       grad_comm_tail="int8")
        assert "tail_comm=int8" in sched.describe()
        sched = _build(model, stage=3, hpz=True, granule_of=GRAN2,
                       gather_prefetch=2, hpz_comm="fp8")
        assert "hpz[fp8]" in sched.describe()

    def test_sched_spec_vocabulary(self):
        out = S.parse_sched_spec(
            "grad_comm=auto,grad_buckets=auto,gather_groups=auto,"
            "grad_comm_tail=int8,hpz,hpz_comm=fp8")
        assert out == {
            "grad_comm": "auto", "grad_buckets": "auto",
            "gather_groups": "auto", "grad_comm_tail": "int8",
            "hpz": True, "hpz_comm": "fp8",
        }
        with pytest.raises(ValueError, match="grad_comm_tail"):
            S.parse_sched_spec("grad_comm_tail=auto")


class TestAutoSizing:
    """Quick tier: auto_comm_plan is a pure function of static geometry
    — the DCN-aware sizing rules, unit-tested without a mesh — plus the
    build_schedule / engine resolution seam ("auto" never survives into
    a slot or a describe string)."""

    def test_granule_geometry(self):
        from tiny_deepspeed_tpu.parallel.mesh import granule_geometry
        assert granule_geometry(None, 8) == (1, 8)
        assert granule_geometry({}, 8) == (1, 8)
        assert granule_geometry(GRAN2, 8) == (2, 4)
        # a map whose granules do not divide n gets no 2-hop sizing
        assert granule_geometry({i: i % 3 for i in range(8)}, 8) == (3, 8)
        # degenerate single-granule map is the flat mesh
        assert granule_geometry({i: 0 for i in range(8)}, 8) == (1, 8)

    def test_single_rank_is_fp32(self):
        plan = S.auto_comm_plan(n_shard=1, n_layer=2)
        assert plan["grad_comm"] == "fp32"
        assert plan["grad_buckets"] == 1
        assert plan["gather_inner"] is None

    def test_flat_mesh_plan(self, model):
        plan = S.auto_comm_plan(n_shard=8, n_layer=TINY.n_layer,
                                shapes=model.param_shapes())
        assert plan["grad_comm"] == "int8"
        assert plan["gather_inner"] is None  # flat: 2-hop moves bytes twice
        assert TINY.n_layer % plan["grad_buckets"] == 0
        m = plan["modeled"]
        assert m["grad_wire_bytes"] <= 1.1 * m["grad_wire_bytes_monolithic"]
        assert m["fp32_allreduce_wire_bytes"] > m["grad_wire_bytes"]
        assert m["dcn_frac_est"] == 0.0

    def test_hybrid_mesh_plan(self, model):
        plan = S.auto_comm_plan(n_shard=8, n_layer=TINY.n_layer,
                                shapes=model.param_shapes(),
                                granule_of=GRAN2)
        assert plan["n_granules"] == 2
        assert plan["gather_inner"] == 4  # ici: fat first hop stays on-slice
        # hybrid cap: every bucket sync crosses DCN, so the divisor
        # search is capped at max(2, max_buckets // n_granules)
        assert plan["grad_buckets"] <= max(2, 8 // 2)
        assert plan["modeled"]["dcn_frac_est"] == 1.0

    def test_bucket_divisor_rule(self, model):
        # n_layer=2: only k in {1, 2} are admissible; whatever wins must
        # keep the modeled wire within the padding tolerance
        plan = S.auto_comm_plan(n_shard=8, n_layer=2,
                                shapes=model.param_shapes(),
                                max_buckets=8)
        assert plan["grad_buckets"] in (1, 2)
        # no shapes -> no byte model -> conservative 1 bucket
        plan = S.auto_comm_plan(n_shard=8, n_layer=2)
        assert plan["grad_buckets"] == 1 and "modeled" not in plan

    def test_build_resolves_auto(self, model):
        sched = _build(model, stage=3, grad_comm="auto",
                       grad_buckets="auto")
        assert sched.grad is not None and sched.grad.mode == "int8"
        assert sched.grad.buckets >= 1
        assert sched.auto_plan is not None
        assert "auto" not in sched.describe()

    def test_auto_buckets_under_explicit_fp32(self, model):
        # a plain fp32 all-reduce program has no bucket machinery to
        # size: auto buckets resolve to 1 and no grad slot is declared
        sched = _build(model, grad_comm="fp32", grad_buckets="auto")
        assert sched.grad is None and sched.lowering == "plain"

    def test_auto_groups_only_on_legacy_prefetch(self, model):
        # single-slot prefetch on the hybrid mesh: auto -> inner=ici
        sched = _build(model, stage=3, gather_prefetch=2,
                       gather_groups="auto", granule_of=GRAN2)
        assert sched.lowering == "prefetch"
        assert sched.gather.groups == 4
        # any composition: the composed machine refuses 2-hop groups,
        # so auto resolves to flat instead of a ScheduleConflictError
        sched = _build(model, stage=3, gather_prefetch=2,
                       gather_groups="auto", grad_comm="int8",
                       granule_of=GRAN2)
        assert sched.lowering == "composed"
        assert sched.gather.groups is None

    def test_engine_auto_resolution(self, model):
        eng = Zero3(model, AdamW(lr=1e-3), grad_comm="auto",
                    grad_buckets="auto", gather_prefetch=2)
        # the engine reads the RESOLVED knobs back off the schedule —
        # telemetry/bench fingerprints never see the literal "auto"
        assert eng.grad_comm == "int8"
        assert isinstance(eng.grad_buckets, int)
        assert "auto" not in eng.describe()
        assert eng._schedule.auto_plan["grad_comm"] == "int8"


class TestCommPlanRoundTrip:
    """Quick tier: the AOT-cache seam — a tune_e2e comm plan merged into
    the store survives save/load and feeds straight back into an engine
    via comm_plan_engine_kwargs (the acceptance round-trip)."""

    def test_store_merge_save_load_build(self, model, tmp_path):
        from tiny_deepspeed_tpu.autotuner import (
            RuntimeAutoTuner, plan_key,
        )
        t = RuntimeAutoTuner(warmup=1, iters=1)
        key = plan_key("tiny", "cpu8", "cpu")
        # phase 1 (train knobs), then the comm phase folds in on top
        t.store_plan(key, {"micro_batch": 8}, {"phase": "train"})
        h = t.store_plan(
            key,
            {"grad_comm": "int8", "grad_buckets": 2,
             "grad_comm_tail": "int8", "gather_prefetch": 2},
            {"comm_score_tuned": 1.0}, merge=True)
        assert h
        path = str(tmp_path / "plans.json")
        t.save(path)
        t2 = RuntimeAutoTuner(warmup=1, iters=1)
        t2.load(path)
        entry = t2.get_plan(key)
        assert entry["plan"]["micro_batch"] == 8  # merge kept phase 1
        assert entry["record"]["phase"] == "train"
        assert entry["record"]["comm_score_tuned"] == 1.0
        kw = S.comm_plan_engine_kwargs(entry["plan"])
        assert kw == {"grad_comm": "int8", "grad_buckets": 2,
                      "grad_comm_tail": "int8", "gather_prefetch": 2}
        eng = Zero3(model, AdamW(lr=1e-3), **kw)
        assert eng._lowering == "composed"
        assert "tail_comm=int8" in eng._schedule.describe()

    def test_plan_keys_cover_the_comm_space(self):
        # ONE list shared by bench's comm phase and this round-trip:
        # every knob the tuner may persist is an engine kwarg
        assert set(S.COMM_PLAN_KEYS) == {
            "grad_comm", "grad_buckets", "grad_comm_tail",
            "gather_groups", "gather_prefetch", "hpz", "hpz_comm",
        }
        assert S.comm_plan_engine_kwargs(
            {"grad_comm": "int8", "gather_groups": None, "junk": 3}
        ) == {"grad_comm": "int8"}


@pytest.mark.slow
class TestTailQuant:
    """Acceptance (wire agenda): the composed ZeRO-3 non-block tail
    releases through the PR-7 blockwise codec with its own residual
    slice — total loop+tail grad wire >= 3x lower than fp32, 20-step
    parity < 5%, and the fp32 path stays HLO-identical when off."""

    def test_off_path_hlo_identical(self, model):
        def hlo(**kw):
            eng = Zero3(model, AdamW(lr=1e-3), gather_prefetch=2,
                        grad_buckets=2, grad_comm="int8", **kw)
            state = eng.init(jax.random.PRNGKey(0))
            return eng._step.lower(state, make_batch()).as_text()
        assert hlo() == hlo(grad_comm_tail="fp32")

    def test_tail_parity_residual_and_wire_pin(self, model):
        from tiny_deepspeed_tpu.utils.hlo_comm import (
            collective_ledger, overlap_report,
        )
        base, _ = run_curve(Zero3(model, AdamW(lr=1e-3)))

        def measure(**kw):
            eng = Zero3(model, AdamW(lr=1e-3), gather_prefetch=2,
                        grad_buckets=2, **kw)
            assert eng._lowering == "composed"
            comp, state = run_curve(eng)
            txt = eng._step.lower(state, make_batch()).compile().as_text()
            rep = overlap_report(txt, led=collective_ledger(txt))
            return comp, state, eng, rep["reduce_wire_bytes_total"]

        comp32, _, _, w32 = measure()
        compq, state, eng, wq = measure(grad_comm="int8",
                                        grad_comm_tail="int8")
        assert max(abs(a - b) for a, b in zip(base, comp32)) < 1e-4
        assert abs(compq[-1] - base[-1]) / abs(base[-1]) < 0.05
        # acceptance: composed ZeRO-3 grad wire INCLUDING the tail
        # (total reduce wire: in-scan bucket syncs + the once-per-step
        # tail release outside the scans) >= 3x lower quantized
        assert w32 / wq >= 3.0
        # the residual grew a tail slice (vs the no-tail pin in
        # test_int8_compose_parity_and_residual)
        lay = eng._schedule.layout
        assert state.grad_residual.shape == (
            8, 2 * lay["bucket_pad"] + lay["tail_pad"])

    def test_tail_gauge_via_capture_compiled(self, model):
        telem = Telemetry()
        eng = Zero3(model, AdamW(lr=1e-3), gather_prefetch=2,
                    grad_buckets=2, grad_comm="int8",
                    grad_comm_tail="int8", telemetry=telem)
        state = eng.init(jax.random.PRNGKey(0))
        telem.capture_compiled(state, make_batch())
        assert telem.gauges["zero3_tail_wire_bytes"] > 0.0


@pytest.mark.slow
class TestHpzQuant:
    """Acceptance (qwZ, ZeRO++ arXiv:2306.10209): the hpZ secondary
    rebuild's inter-slice all_gather moves fp8 blocks + scales — its
    DCN wire >= 3x lower than fp32, loss parity < 5%."""

    def _rebuild_wire(self, model, **kw):
        from tiny_deepspeed_tpu.utils.hlo_comm import (
            collective_ledger, group_wire_outside_loops,
        )
        eng = Zero3(model, AdamW(lr=1e-3), hpz=True,
                    hpz_granule_of=GRAN2, gather_prefetch=2, **kw)
        comp, state = run_curve(eng)
        txt = eng._step.lower(state, make_batch()).compile().as_text()
        # the rebuild hop ISOLATED: outside-loop wire on exactly the
        # inter-granule replica groups (the tail gathers share the DCN
        # link but run over different groups)
        inter = eng._schedule.hpz_geom[1]
        return comp, group_wire_outside_loops(collective_ledger(txt),
                                              inter)

    def test_fp8_rebuild_dcn_pin_and_parity(self, model):
        base, _ = run_curve(Zero3(model, AdamW(lr=1e-3)))
        c32, w32 = self._rebuild_wire(model)
        c8, w8 = self._rebuild_wire(model, hpz_comm="fp8")
        assert max(abs(a - b) for a, b in zip(base, c32)) < 1e-4
        assert abs(c8[-1] - base[-1]) / abs(base[-1]) < 0.05
        assert w32 > 0.0 and w8 > 0.0
        # fp8 blocks + f32 scale rows vs f32 shards: ~4x, pinned >= 3x
        assert w32 / w8 >= 3.0

    def test_rebuild_gauge_via_capture_compiled(self, model):
        telem = Telemetry()
        eng = Zero3(model, AdamW(lr=1e-3), hpz=True,
                    hpz_granule_of=GRAN2, hpz_comm="fp8",
                    telemetry=telem)
        state = eng.init(jax.random.PRNGKey(0))
        telem.capture_compiled(state, make_batch(), granule_of=GRAN2)
        assert telem.gauges["hpz_rebuild_dcn_bytes"] > 0.0
        assert telem.gauges["hpz_dcn_wire_bytes"] == 0.0
