# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Parity tests for the hand-written FA2 kernel (ops/flash_fa2.py).

Runs in Pallas `interpret=True` mode on the CPU mesh (no Mosaic backend
there); the real-chip numbers are in BASELINE.md.  Reference semantics:
softmax(QK^T/sqrt(d)) with a causal mask, i.e. exactly
`ops.attention.standard_attention`."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu.ops import flash_fa2
from tiny_deepspeed_tpu.ops.attention import standard_attention
from tiny_deepspeed_tpu.ops.flash_fa2 import fa2_flash_attention


@pytest.fixture(autouse=True)
def _interpret():
    old = flash_fa2._INTERPRET
    flash_fa2._INTERPRET = True
    yield
    flash_fa2._INTERPRET = old


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


class TestFA2:
    def test_forward_matches_standard(self):
        q, k, v = (_rand((2, 3, 256, 64), i) for i in range(3))
        np.testing.assert_allclose(
            np.asarray(fa2_flash_attention(q, k, v, 128, 128)),
            np.asarray(standard_attention(q, k, v)), rtol=2e-5, atol=2e-5)

    def test_grads_match_standard(self):
        q, k, v = (_rand((1, 2, 256, 64), i) for i in range(3))
        g1 = jax.grad(lambda *a: jnp.sum(fa2_flash_attention(*a, 128, 128) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(standard_attention(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5,
                err_msg=f"d{name}")

    def test_uneven_blocks(self):
        """block_q != block_k exercises the diagonal-straddling masks."""
        q, k, v = (_rand((1, 1, 512, 64), i) for i in range(3))
        np.testing.assert_allclose(
            np.asarray(fa2_flash_attention(q, k, v, 256, 128)),
            np.asarray(standard_attention(q, k, v)), rtol=2e-5, atol=2e-5)

    def test_small_t_single_block(self):
        """T smaller than any block: _pick degrades to one full block."""
        q, k, v = (_rand((2, 2, 64, 64), i) for i in range(3))
        np.testing.assert_allclose(
            np.asarray(fa2_flash_attention(q, k, v, 512, 512)),
            np.asarray(standard_attention(q, k, v)), rtol=2e-5, atol=2e-5)

    def test_bf16_inputs(self):
        q, k, v = (_rand((1, 2, 256, 64), i, jnp.bfloat16) for i in range(3))
        o = fa2_flash_attention(q, k, v, 128, 128)
        assert o.dtype == jnp.bfloat16
        ref = standard_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(o, np.float32), np.asarray(ref, np.float32),
            rtol=0.05, atol=0.02)

    def test_composes_with_remat(self):
        """jax.checkpoint over the kernel (the block remat path)."""
        q, k, v = (_rand((1, 1, 128, 64), i) for i in range(3))
        f = jax.checkpoint(
            lambda q, k, v: jnp.sum(fa2_flash_attention(q, k, v, 128, 128)))
        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(
            lambda q, k, v: jnp.sum(standard_attention(q, k, v)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5)

    def test_tuner_variant_guards_long_t(self, monkeypatch):
        """FLASH_VARIANTS must be T-safe at ANY length: the tuner's
        candidates[0]/frozen fallbacks dispatch without timing, so the FA2
        entries fall back to the blocked bundled kernel past FA2_MAX_T
        instead of compiling FA2's full VMEM panels."""
        from tiny_deepspeed_tpu.ops import attention_pallas as ap

        calls = []
        monkeypatch.setattr(
            ap, "pallas_flash_attention",
            lambda q, k, v, **kw: calls.append("bundled") or q)
        monkeypatch.setattr(
            flash_fa2, "fa2_flash_attention",
            lambda q, k, v, *a: calls.append("fa2") or q)
        fa2_variant = next(f for f in ap.FLASH_VARIANTS
                           if f.__name__.startswith("fa2"))
        long_t = jnp.zeros((1, 1, ap.FA2_MAX_T + 1024, 64), jnp.bfloat16)
        short_t = jnp.zeros((1, 1, 256, 64), jnp.bfloat16)
        fa2_variant(long_t, long_t, long_t)
        fa2_variant(short_t, short_t, short_t)
        assert calls == ["bundled", "fa2"]

    def test_bthd_layout_matches_bhtd(self):
        """The heads-last entry must be bit-for-bit the standard entry's
        result transposed — fwd and all three grads.  The _ah kernels
        loop heads statically over whole (T, H*Dh) panels but perform
        the identical f32 operation sequence per head, so exact equality
        is the contract, not an accident."""
        from tiny_deepspeed_tpu.ops.flash_fa2 import fa2_flash_attention_bthd
        q, k, v = (_rand((2, 2, 256, 64), i) for i in range(3))
        qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))  # (B, T, H, Dh)
        o_std = fa2_flash_attention(q, k, v, 128, 128)
        o_hl = fa2_flash_attention_bthd(qt, kt, vt, 128, 128)
        np.testing.assert_array_equal(np.asarray(o_hl.swapaxes(1, 2)),
                                      np.asarray(o_std))
        g_std = jax.grad(lambda *a: jnp.sum(fa2_flash_attention(*a, 128, 128) ** 2),
                         argnums=(0, 1, 2))(q, k, v)
        g_hl = jax.grad(lambda *a: jnp.sum(fa2_flash_attention_bthd(*a, 128, 128) ** 2),
                        argnums=(0, 1, 2))(qt, kt, vt)
        for name, a, b in zip("qkv", g_std, g_hl):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b.swapaxes(1, 2)),
                rtol=1e-6, atol=1e-7, err_msg=f"d{name}")

    @pytest.mark.slow  # tier-1 budget: the BTHD-vs-BHTD parity pin
    # stays quick; the VMEM-budget fallback path runs in the full tier
    def test_bthd_fallback_past_vmem_budget(self, monkeypatch):
        """Past _AH_MAX_T_HD the entry transposes over to the standard
        kernels — same numbers, different plumbing."""
        from tiny_deepspeed_tpu.ops.flash_fa2 import fa2_flash_attention_bthd
        monkeypatch.setattr(flash_fa2, "_AH_MAX_T_HD", 1)  # force fallback
        q, k, v = (_rand((1, 2, 256, 64), i) for i in range(3))
        qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
        np.testing.assert_array_equal(
            np.asarray(fa2_flash_attention_bthd(qt, kt, vt, 128, 128)
                       .swapaxes(1, 2)),
            np.asarray(fa2_flash_attention(q, k, v, 128, 128)))
        g_hl = jax.grad(lambda *a: jnp.sum(
            fa2_flash_attention_bthd(*a, 128, 128) ** 2),
            argnums=(0, 1, 2))(qt, kt, vt)
        g_std = jax.grad(lambda *a: jnp.sum(
            fa2_flash_attention(*a, 128, 128) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_std, g_hl):
            np.testing.assert_allclose(np.asarray(a),
                                       np.asarray(b.swapaxes(1, 2)),
                                       rtol=1e-6, atol=1e-7)

    def test_gqa_matches_repeat_path(self):
        """GQA-native kernel (k/v at KVH heads) vs jnp.repeat + the MHA
        kernel: forward and all three grads.  dk/dv must come back at
        KVH heads — the in-kernel group sum is the repeat's vjp."""
        B, H, KVH, T, D = 2, 6, 2, 256, 64
        q = _rand((B, H, T, D), 0)
        k = _rand((B, KVH, T, D), 1)
        v = _rand((B, KVH, T, D), 2)
        rep = H // KVH

        def ref(q, k, v):
            return fa2_flash_attention(
                q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
                128, 128)

        np.testing.assert_allclose(
            np.asarray(fa2_flash_attention(q, k, v, 128, 128)),
            np.asarray(ref(q, k, v)), rtol=1e-6, atol=1e-7)
        g1 = jax.grad(lambda *a: jnp.sum(fa2_flash_attention(*a, 128, 128) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        assert g1[1].shape == (B, KVH, T, D)
        assert g1[2].shape == (B, KVH, T, D)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5,
                err_msg=f"d{name}")

    def test_gqa_uneven_blocks(self):
        """GQA with block_q != block_k (diagonal-straddling masks) and a
        group that isn't a power of two (llama-160m's is 3)."""
        q = _rand((1, 6, 512, 64), 0)
        k = _rand((1, 2, 512, 64), 1)
        v = _rand((1, 2, 512, 64), 2)
        ref = fa2_flash_attention(
            q, jnp.repeat(k, 3, axis=1), jnp.repeat(v, 3, axis=1), 256, 128)
        np.testing.assert_allclose(
            np.asarray(fa2_flash_attention(q, k, v, 256, 128)),
            np.asarray(ref), rtol=1e-6, atol=1e-7)

    def test_gqa_supported_bound(self):
        """The dkv VMEM guard: group*t*d over 2M elements says no."""
        from tiny_deepspeed_tpu.ops.flash_fa2 import fa2_gqa_supported
        assert fa2_gqa_supported(2048, 64, 4)        # llama-1b shape
        assert fa2_gqa_supported(16384, 64, 1)       # == FA2_MAX_T
        assert not fa2_gqa_supported(16384, 64, 4)   # panels over budget

    def test_lse_residual_shape(self):
        """The whole point: the stashed stat is ONE (B*H, 1, T) f32 tensor."""
        q, k, v = (_rand((2, 3, 256, 64), i) for i in range(3))
        out, (res_q, res_k, res_v, o, lse) = flash_fa2._fa2_fwd(
            q, k, v, 128, 128)
        assert lse.shape == (2 * 3, 1, 256)
        assert lse.dtype == jnp.float32
        # lse really is logsumexp of the masked scores
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(64)
        mask = jnp.tril(jnp.ones((256, 256), bool))
        s = jnp.where(mask, s, -jnp.inf)
        ref = jax.nn.logsumexp(s, axis=-1).reshape(6, 1, 256)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
