# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Resilience: atomic/async checkpointing, chaos-driven recovery, elastic
(mesh-shape-changing) resume, preemption drain, straggler mitigation.

Every recovery path is exercised by ACTUALLY breaking things through the
chaos harness (tiny_deepspeed_tpu/resilience/chaos.py) — injected write
failures, a writer killed between tmp-write and commit, NaN'd params,
an in-process SIGTERM — not by mocking the failure's observers."""

import dataclasses
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import AdamW, GPTConfig, GPT2Model, Zero1, Zero2, \
    Zero3
from tiny_deepspeed_tpu.data import TokenLoader
from tiny_deepspeed_tpu.parallel.mesh import make_mesh
from tiny_deepspeed_tpu.resilience import (
    Chaos, ChaosEngine, CheckpointManager, PreemptionGuard,
    check_reshapeable, data_offset_batches, elastic_load,
    rebalance_shares, ShardRebalancer,
)
from tiny_deepspeed_tpu.telemetry import Telemetry
from tiny_deepspeed_tpu.utils.checkpoint import (
    COMMIT_MARKER, CheckpointKilled, latest_step, list_steps,
    load_checkpoint, read_meta, save_checkpoint, set_io_hook,
)

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)


def batch(i, b=8):
    k = jax.random.split(jax.random.PRNGKey(100 + i), 2)
    return (jax.random.randint(k[0], (b, 32), 0, 128),
            jax.random.randint(k[1], (b, 32), 0, 128))


@pytest.fixture(scope="module")
def model():
    return GPT2Model(TINY)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(devices=jax.devices()[:4])


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(devices=jax.devices())


@pytest.fixture(scope="module")
def eng2_4(model, mesh4):
    """Shared Zero2 engine on 4 devices (one XLA compile for the module)."""
    return Zero2(model, AdamW(lr=1e-3), mesh=mesh4)


@pytest.fixture(autouse=True)
def _clean_io_hook():
    yield
    set_io_hook(None)  # no chaos leaks across tests


# ---------------------------------------------------------------------------
# atomic commit + partial-dir skipping (satellite: latest_step trusting any
# step_* name used to crash restore)
# ---------------------------------------------------------------------------

class TestAtomicCommit:
    def _tree(self):
        return {"w": jnp.arange(8, dtype=jnp.float32), "n": jnp.int32(3)}

    def test_commit_marker_and_meta(self, tmp_path):
        d = str(tmp_path)
        path = save_checkpoint(d, self._tree(), 5, meta={"step": 5})
        assert os.path.exists(os.path.join(path, COMMIT_MARKER))
        assert latest_step(d) == 5
        assert read_meta(d, 5) == {"step": 5}
        assert read_meta(d, 99) is None

    def test_partial_dirs_skipped_not_crashed(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, self._tree(), 3)
        # a crashed writer's leavings: empty dir with a LARGER step number
        # (used to win max(steps) and crash the restore), plus junk
        os.makedirs(os.path.join(d, "step_00000009"))
        os.makedirs(os.path.join(d, "step_garbage"))
        committed, skipped = list_steps(d)
        assert committed == [3]
        assert "step_00000009" in skipped and "step_garbage" in skipped
        assert latest_step(d) == 3
        tree = self._tree()
        target = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
        )
        restored = load_checkpoint(d, target=target)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))

    def test_only_partials_raises_naming_them(self, tmp_path):
        d = str(tmp_path)
        os.makedirs(os.path.join(d, "step_00000007"))
        with pytest.raises(FileNotFoundError, match="step_00000007"):
            load_checkpoint(d, target=None)
        assert latest_step(d) is None

    def test_explicit_uncommitted_step_refused(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, self._tree(), 1)
        os.makedirs(os.path.join(d, "step_00000002"))
        with pytest.raises(FileNotFoundError, match="not committed"):
            load_checkpoint(d, target=None, step=2)

    def test_rename_without_marker_is_uncommitted(self, tmp_path):
        """The second crash window: dir renamed to its final name but the
        writer died before the marker — both our marker and Orbax's
        finalize artifact must be absent for the skip to trigger."""
        d = str(tmp_path)
        path = save_checkpoint(d, self._tree(), 4)
        os.remove(os.path.join(path, COMMIT_MARKER))
        meta = os.path.join(path, "_CHECKPOINT_METADATA")
        if os.path.exists(meta):
            os.remove(meta)
        assert latest_step(d) is None


# ---------------------------------------------------------------------------
# bounded retry + backoff (satellite), chaos write failures
# ---------------------------------------------------------------------------

class TestRetryBackoff:
    def test_transient_failures_retried_and_counted(self, tmp_path):
        telem = Telemetry(flight_steps=0)
        chaos = Chaos(ckpt_write_failures=2)
        with chaos:
            path = save_checkpoint(
                str(tmp_path), {"w": jnp.zeros(4)}, 1,
                retries=3, backoff=0.01, telemetry=telem,
            )
        assert os.path.exists(os.path.join(path, COMMIT_MARKER))
        assert telem.counters["checkpoint_retries"].value == 2
        assert [r["fault"] for r in chaos.injected] \
            == ["ckpt_write_failure"] * 2

    def test_exhausted_retries_name_path_and_attempts(self, tmp_path):
        chaos = Chaos(ckpt_write_failures=99)
        with chaos, pytest.raises(RuntimeError) as ei:
            save_checkpoint(str(tmp_path), {"w": jnp.zeros(4)}, 7,
                            retries=1, backoff=0.01)
        msg = str(ei.value)
        assert "step_00000007" in msg and "2 attempt" in msg
        assert latest_step(str(tmp_path)) is None

    def test_uncommitted_final_dir_cleaned_on_each_attempt(self, tmp_path):
        """An attempt that dies between os.rename and the COMMITTED
        marker leaves a non-empty uncommitted dir at the FINAL path; the
        next retry must clean it again or its own rename fails with
        ENOTEMPTY and a one-shot transient error exhausts every retry."""
        d = str(tmp_path)
        path = os.path.join(d, "step_00000003")

        def hook(phase, p, attempt):
            if phase == "write" and attempt == 0:
                os.makedirs(path, exist_ok=True)
                with open(os.path.join(path, "junk"), "w") as f:
                    f.write("partial payload, no marker")
                raise OSError("transient blip")

        set_io_hook(hook)
        out = save_checkpoint(d, {"w": jnp.zeros(4)}, 3,
                              retries=2, backoff=0.01)
        assert out == path and latest_step(d) == 3
        assert not os.path.exists(os.path.join(path, "junk"))


# ---------------------------------------------------------------------------
# crash mid-save (satellite): killed between tmp-write and commit; the next
# restore lands on the previous good step and training continues bit-exact
# ---------------------------------------------------------------------------

class TestCrashMidSave:
    def test_kill_between_tmp_write_and_commit(self, tmp_path, eng2_4):
        d = str(tmp_path)
        s = eng2_4.init(jax.random.PRNGKey(0))
        s, _ = eng2_4.step(s, batch(0))
        save_checkpoint(d, s, 1)

        # uninterrupted reference from the committed point (the step
        # donates its input buffers, so each trajectory restores its own)
        ref = load_checkpoint(d, eng2_4)
        for i in range(1, 3):
            ref, loss_ref = eng2_4.step(ref, batch(i))

        s = load_checkpoint(d, eng2_4)
        s, _ = eng2_4.step(s, batch(1))
        chaos = Chaos().install()
        chaos.kill_next_commit()
        with pytest.raises(CheckpointKilled):
            save_checkpoint(d, s, 2)
        chaos.uninstall()
        # the payload was fully written, but never committed: only the
        # dot-prefixed tmp dir exists and the resume chain still ends at 1
        assert latest_step(d) == 1
        assert any(n.startswith(".tmp_step_") for n in os.listdir(d))

        restored = load_checkpoint(d, eng2_4)
        for i in range(1, 3):
            restored, loss_res = eng2_4.step(restored, batch(i))
        assert float(loss_res) == float(loss_ref)


# ---------------------------------------------------------------------------
# async save + adaptive cadence (CheckpointManager)
# ---------------------------------------------------------------------------

class _SlowWrites:
    """io hook that stalls the write phase — keeps the async writer thread
    observably in flight without depending on disk speed."""

    def __init__(self, delay_s):
        self.delay_s = delay_s

    def __call__(self, phase, path, attempt):
        if phase == "write":
            time.sleep(self.delay_s)


class TestCheckpointManager:
    def test_async_save_snapshots_before_donation(self, tmp_path, eng2_4):
        """The async writer must persist the state AS OF the save call:
        the engine's jitted step donates the old state's buffers, so the
        manager snapshots to host before kicking the thread.  Training
        steps taken while the write is in flight must not change what
        lands on disk."""
        d = str(tmp_path)
        s = eng2_4.init(jax.random.PRNGKey(0))
        s, _ = eng2_4.step(s, batch(0))
        w_at_save = np.asarray(s.params["wte"]).copy()
        set_io_hook(_SlowWrites(0.2))
        with CheckpointManager(d, engine=eng2_4) as mgr:
            mgr.save(s, 1)
            # step twice while the write is in flight (donates s's buffers)
            for i in range(1, 3):
                s, _ = eng2_4.step(s, batch(i))
                mgr.note_step()
            assert mgr.overlap_steps >= 1  # steps hidden behind I/O
        set_io_hook(None)
        restored = load_checkpoint(d, eng2_4, step=1)
        np.testing.assert_array_equal(
            np.asarray(restored.params["wte"]), w_at_save
        )
        meta = read_meta(d, 1)
        assert meta["elastic"]["mesh"]["n_devices"] == 4

    def test_background_failure_surfaces_on_next_call(self, tmp_path):
        chaos = Chaos(ckpt_write_failures=99).install()
        mgr = CheckpointManager(str(tmp_path), retries=0, backoff=0.01)
        mgr.save({"w": jnp.zeros(4)}, 1)
        with pytest.raises(RuntimeError, match="background checkpoint"):
            mgr.wait()
        chaos.uninstall()

    def test_interval_and_anomaly_cadence(self, tmp_path):
        telem = Telemetry(flight_steps=8)
        mgr = CheckpointManager(str(tmp_path), every=4, telemetry=telem,
                                async_save=False)
        tree = {"w": jnp.zeros(4)}
        assert mgr.maybe_save(tree, 1) is None
        assert mgr.maybe_save(tree, 4) == "interval"
        # flight-recorder anomaly (slow step): checkpoint immediately,
        # off-interval — and edge-triggered, not once per later step
        telem.flight_pending = "slow_step"
        assert mgr.maybe_save(tree, 6) == "anomaly:slow_step"
        assert mgr.maybe_save(tree, 7) is None
        assert latest_step(str(tmp_path)) == 6
        assert telem.counters["checkpoint_saves"].value == 2
        assert telem.gauges["checkpoint_last_step"] == 6

    def test_force_drain_not_fooled_by_failed_async_save(self, tmp_path):
        """last_saved_step records an ENQUEUE, not a commit: when the
        in-flight interval save fails, the SIGTERM drain at the same
        step must still produce a committed checkpoint (warning about
        the earlier failure) instead of trusting the dedup and exiting
        with nothing on disk."""
        d = str(tmp_path)
        chaos = Chaos(ckpt_write_failures=1).install()
        mgr = CheckpointManager(d, every=1, retries=0, backoff=0.01)
        tree = {"w": jnp.zeros(4)}
        assert mgr.maybe_save(tree, 1) == "interval"  # enqueued; will fail
        mgr._thread.join()  # let the failure land (stays pending)
        with pytest.warns(UserWarning, match="background checkpoint"):
            assert mgr.maybe_save(tree, 1, force=True) == "final"
        chaos.uninstall()
        mgr.close()
        assert latest_step(d) == 1

    def test_anomaly_latch_consumed_and_retriggers(self, tmp_path):
        """The manager CONSUMES telemetry.flight_pending when no flight
        flusher ran first (no metrics logger): clearing the latch re-arms
        the registry's edge trigger, so a SECOND anomaly episode fires a
        second checkpoint instead of being swallowed forever."""
        telem = Telemetry(flight_steps=8)
        mgr = CheckpointManager(str(tmp_path), telemetry=telem,
                                async_save=False)
        tree = {"w": jnp.zeros(4)}
        telem.flight_pending = "slow_step"
        assert mgr.maybe_save(tree, 3) == "anomaly:slow_step"
        assert telem.flight_pending is None
        assert mgr.maybe_save(tree, 4) is None
        telem.flight_pending = "slow_step"  # new episode, same reason
        assert mgr.maybe_save(tree, 9) == "anomaly:slow_step"
        assert latest_step(str(tmp_path)) == 9

    def test_interval_save_of_nonfinite_state_stays_out_of_chain(
            self, tmp_path):
        """A NaN episode outlives its one edge-triggered anomaly: the
        NEXT interval (or final-drain) save must consult health and route
        the still-poisoned state to postmortem, not the resume chain."""
        d = str(tmp_path)
        telem = Telemetry(flight_steps=8)
        mgr = CheckpointManager(d, every=2, telemetry=telem,
                                async_save=False)
        tree = {"w": jnp.zeros(4)}
        assert mgr.maybe_save(tree, 2) == "interval"
        telem._last_health = {"loss": float("nan"), "nonfinite_grads": 1}
        bad = {"w": jnp.full(4, jnp.nan)}
        # the reason says postmortem — the caller's "saved checkpoint"
        # log must not promise a restore point latest_step can't see
        assert mgr.maybe_save(bad, 4) == "postmortem:interval"
        assert latest_step(d) == 2                       # chain unpoisoned
        assert latest_step(os.path.join(d, "postmortem")) == 4
        assert mgr.maybe_save(bad, 5, force=True) == "postmortem:final"
        assert latest_step(d) == 2
        assert latest_step(os.path.join(d, "postmortem")) == 5
        # drain coinciding with an already-saved postmortem step must not
        # crash on the committed dir — it skips (nothing new to secure)
        assert mgr.maybe_save(bad, 5, force=True) is None

    def test_postmortem_replayed_after_resume_skips_committed_dir(
            self, tmp_path):
        """A resumed deterministic run replays the same NaN step, and the
        duplicate-postmortem latch is process-local: a FRESH manager must
        see the previous process's committed postmortem ON DISK and skip,
        instead of dying on save_checkpoint's already-committed check
        (an opaque background-save failure in the async case)."""
        d = str(tmp_path)
        telem = Telemetry(flight_steps=8)
        mgr = CheckpointManager(d, telemetry=telem, async_save=False)
        bad = {"w": jnp.full(4, jnp.nan)}
        telem.flight_pending = "nonfinite"
        assert mgr.maybe_save(bad, 3) == "anomaly:nonfinite"
        # "restart": a new process = a new manager, no in-memory latch
        telem2 = Telemetry(flight_steps=8)
        mgr2 = CheckpointManager(d, telemetry=telem2, async_save=False)
        telem2.flight_pending = "nonfinite"
        with pytest.warns(UserWarning, match="already committed"):
            assert mgr2.maybe_save(bad, 3) is None
        assert latest_step(os.path.join(d, "postmortem")) == 3

    def test_nonfinite_anomaly_goes_to_postmortem(self, tmp_path):
        """A NaN state is preserved for debugging but must never enter
        the resume chain — latest_step would otherwise restore a NaN."""
        d = str(tmp_path)
        telem = Telemetry(flight_steps=8)
        mgr = CheckpointManager(d, every=2, telemetry=telem,
                                async_save=False)
        tree = {"w": jnp.zeros(4)}
        mgr.maybe_save(tree, 2)
        telem.flight_pending = "nonfinite"
        bad = {"w": jnp.full(4, jnp.nan)}
        assert mgr.maybe_save(bad, 3) == "anomaly:nonfinite"
        assert latest_step(d) == 2                       # chain unpoisoned
        assert latest_step(os.path.join(d, "postmortem")) == 3
        assert telem.counters["checkpoint_postmortems"].value == 1


# ---------------------------------------------------------------------------
# preemption: SIGTERM drains one final committed checkpoint
# ---------------------------------------------------------------------------

class TestPreemption:
    def test_guard_flags_and_restores_handler(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionGuard() as g:
            assert g.active and not g.triggered
            signal.raise_signal(signal.SIGTERM)
            assert g.triggered and g.signum == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is before

    def test_agreed_ors_rank_local_flags_across_hosts(self):
        """The loop drains on `agreed()`, never the raw flag: hosts see
        the preemption notice at different iterations, and a final save
        only some hosts enter deadlocks its collective barriers against
        the others' next step — one host's SIGTERM must drain EVERY host
        at the same loop point, and no-signal-anywhere must drain none."""
        with PreemptionGuard() as g:
            # remote-only signal: local flag False, another host's True
            assert g.agreed(lambda x: np.array([bool(x), True])) is True
            assert g.agreed(lambda x: np.array([bool(x), False])) is False
            signal.raise_signal(signal.SIGTERM)
            assert g.agreed(lambda x: np.array([bool(x), False])) is True
            assert g.agreed() is True  # single-process: local flag, no sync

    def test_sigterm_drain_and_exact_data_offset(self, tmp_path, eng2_4):
        """The acceptance pin: chaos injects SIGTERM mid-run; the loop
        drains a final committed checkpoint carrying the exact global
        sample offset; the resumed run consumes the SAME remaining
        batches as an uninterrupted run — none skipped, none repeated."""
        d = str(tmp_path)
        b, total_iters = 8, 6

        def stream():
            return TokenLoader(None, batch=b, seq=32, vocab_size=128,
                               seed=5, force_numpy=True)

        # uninterrupted reference: 6 steps, recording each batch's ids
        loader = stream()
        ref_batches = []
        s = eng2_4.init(jax.random.PRNGKey(0))
        for _ in range(total_iters):
            x, y = loader.next()
            ref_batches.append(x.copy())
            s, loss_ref = eng2_4.step(s, (jnp.asarray(x), jnp.asarray(y)))

        # chaos run: SIGTERM raised at step 3 -> guard drains at loop end
        chaos = Chaos(sigterm_step=3)
        ce = ChaosEngine(eng2_4, chaos)
        loader = stream()
        s = eng2_4.init(jax.random.PRNGKey(0))
        with PreemptionGuard() as guard, \
                CheckpointManager(d, engine=eng2_4) as mgr:
            for it in range(total_iters):
                x, y = loader.next()
                np.testing.assert_array_equal(x, ref_batches[it])
                s, _ = ce.step(s, (jnp.asarray(x), jnp.asarray(y)))
                if guard.triggered:
                    mgr.maybe_save(
                        s, it + 1, force=True,
                        data_meta={"samples_seen": loader.samples_seen,
                                   "global_batch": b, "seed": 5},
                    )
                    break
        stopped_at = it + 1
        assert stopped_at == 4  # sigterm at 0-based step 3
        assert latest_step(d) == stopped_at
        assert chaos.injected[0]["fault"] == "sigterm"

        # restart: fresh process's view — elastic_load + sample-offset seek
        s2, info = elastic_load(d, eng2_4)
        assert info["resumed_step"] == stopped_at
        assert not info["elastic"]
        off = data_offset_batches(info, b)
        assert off == stopped_at  # data-offset pinned
        loader = stream()
        loader.seek_samples(off * b)
        for it in range(stopped_at, total_iters):
            x, y = loader.next()
            np.testing.assert_array_equal(x, ref_batches[it])  # no skip
            s2, loss_res = eng2_4.step(s2, (jnp.asarray(x), jnp.asarray(y)))
        assert float(loss_res) == float(loss_ref)  # fp32 bit-exact


# ---------------------------------------------------------------------------
# elastic (mesh-shape-changing) resume — the tentpole acceptance pin
# ---------------------------------------------------------------------------

class TestElasticResume:
    # tier-1 budget: the Zero3 grow variant is the heaviest test in the
    # quick tier (~24s) and its unique coverage — Zero3 partition-table
    # rederivation on a CHANGED mesh — is kept quick by
    # test_shrink_8_to_4_devices (Zero3, the other direction); Zero1/
    # Zero2 keep the grow path itself quick
    @pytest.mark.parametrize("engine_cls", [
        Zero1, Zero2, pytest.param(Zero3, marks=pytest.mark.slow)])
    def test_grow_4_to_8_devices_loss_parity(self, engine_cls, model,
                                             mesh4, mesh8, tmp_path):
        """Train K steps on 4 devices, checkpoint, restore onto 8,
        continue K — the final loss matches an uninterrupted 2K-step run
        (fp32 deterministic path: < 1e-4)."""
        d = str(tmp_path)
        K = 3
        eng_n = engine_cls(model, AdamW(lr=1e-3), mesh=mesh4)
        s = eng_n.init(jax.random.PRNGKey(0))
        for i in range(K):
            s, _ = eng_n.step(s, batch(i))
        mgr = CheckpointManager(d, engine=eng_n, async_save=False)
        mgr.save(s, K, data_meta={"samples_seen": K * 8,
                                  "global_batch": 8, "seed": 0})

        eng_m = engine_cls(model, AdamW(lr=1e-3), mesh=mesh8)
        s2, info = elastic_load(d, eng_m)
        assert info["elastic"] and info["old_mesh"]["n_devices"] == 4
        assert info["new_mesh"]["n_devices"] == 8
        assert data_offset_batches(info, 8) == K
        # optimizer state landed in the NEW mesh's ZeRO sharding and the
        # step counter carried over
        assert int(s2.opt_state["step"]) == K
        m = s2.opt_state["state"]["h.mlp.fc.w"]["m"]
        assert np.prod(m.sharding.shard_shape(m.shape)) * 8 \
            == np.prod(m.shape)
        for i in range(K, 2 * K):
            s2, loss_res = eng_m.step(s2, batch(i))

        ref = eng_m.init(jax.random.PRNGKey(0))
        for i in range(2 * K):
            ref, loss_ref = eng_m.step(ref, batch(i))
        assert abs(float(loss_res) - float(loss_ref)) < 1e-4

    def test_shrink_8_to_4_devices(self, model, mesh4, mesh8, tmp_path):
        """The preemption direction: the slice came back SMALLER."""
        d = str(tmp_path)
        eng_n = Zero3(model, AdamW(lr=1e-3), mesh=mesh8)
        s = eng_n.init(jax.random.PRNGKey(0))
        for i in range(2):
            s, _ = eng_n.step(s, batch(i))
        CheckpointManager(d, engine=eng_n, async_save=False).save(s, 2)

        eng_m = Zero3(model, AdamW(lr=1e-3), mesh=mesh4)
        s2, info = elastic_load(d, eng_m)
        assert info["elastic"] and info["moved_params"] > 0
        for i in range(2, 4):
            s2, loss_res = eng_m.step(s2, batch(i))
        ref = eng_m.init(jax.random.PRNGKey(0))
        for i in range(4):
            ref, loss_ref = eng_m.step(ref, batch(i))
        assert abs(float(loss_res) - float(loss_ref)) < 1e-4

    def test_refusal_names_both_meshes(self, eng2_4):
        """Configs that pin state to mesh positions refuse loudly, with
        the old AND new shapes in the message."""
        saved = {
            "engine": "Zero2", "stage": 2, "n_shard": 4,
            "mesh": {"axes": {"data": 4, "pipe": 2}, "n_devices": 8,
                     "n_processes": 1},
            "residual_shape": None,
        }
        with pytest.raises(ValueError) as ei:
            check_reshapeable(saved, eng2_4)
        msg = str(ei.value)
        assert "pipe" in msg and "data=4" in msg and "pipe=2" in msg
        assert "data=4 (4 devices)" in msg  # the new mesh, named too

    def test_same_mesh_is_not_elastic(self, eng2_4, tmp_path):
        s = eng2_4.init(jax.random.PRNGKey(1))
        CheckpointManager(str(tmp_path), engine=eng2_4,
                          async_save=False).save(s, 1)
        _, info = elastic_load(str(tmp_path), eng2_4)
        assert not info["elastic"]
        assert info["residual_action"] == "kept"

    def test_legacy_checkpoint_without_meta_warns(self, eng2_4, tmp_path):
        s = eng2_4.init(jax.random.PRNGKey(1))
        save_checkpoint(str(tmp_path), s, 1)  # no meta sidecar
        with pytest.warns(UserWarning, match="no elastic descriptor"):
            _, info = elastic_load(str(tmp_path), eng2_4)
        assert info["old_mesh"] is None

    def test_residual_rederived_on_topology_change(self, model, mesh4,
                                                   mesh8, tmp_path):
        """grad_comm error-feedback residual is (n_dev, pad)-shaped: a
        topology change re-derives it (zeroed) instead of crashing the
        restore or silently mis-sharding it."""
        d = str(tmp_path)
        eng_n = Zero2(model, AdamW(lr=1e-3), mesh=mesh4, grad_comm="int8")
        s = eng_n.init(jax.random.PRNGKey(0))
        s, _ = eng_n.step(s, batch(0))
        assert s.grad_residual.shape[0] == 4
        CheckpointManager(d, engine=eng_n, async_save=False).save(s, 1)

        eng_m = Zero2(model, AdamW(lr=1e-3), mesh=mesh8, grad_comm="int8")
        with pytest.warns(UserWarning, match="re-derived"):
            s2, info = elastic_load(d, eng_m)
        assert info["residual_action"] == "rederived"
        assert s2.grad_residual.shape[0] == 8
        assert float(jnp.sum(jnp.abs(s2.grad_residual))) == 0.0
        s2, loss = eng_m.step(s2, batch(1))
        assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# data offsets: exact resume across batch-size changes (indexed loader)
# ---------------------------------------------------------------------------

class TestDataOffsets:
    def test_indexed_stream_is_batch_size_invariant(self):
        """Sample g of the indexed stream is the same array no matter how
        the stream is batched — the property that makes a mesh change
        (new global batch) resume with nothing skipped or repeated."""
        a = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=3,
                        indexed=True)
        xs_a = np.concatenate([a.next()[0] for _ in range(6)])  # 24 samples
        b = TokenLoader(None, batch=8, seq=16, vocab_size=64, seed=3,
                        indexed=True)
        xs_b = np.concatenate([b.next()[0] for _ in range(3)])  # 24 samples
        np.testing.assert_array_equal(xs_a, xs_b)

    def test_indexed_seek_any_offset(self):
        a = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=3,
                        indexed=True)
        for _ in range(3):
            a.next()
        nxt = a.next()[0]  # samples 12..15
        b = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=3,
                        indexed=True)
        b.seek_samples(12)
        np.testing.assert_array_equal(b.next()[0], nxt)
        # arbitrary (not batch-aligned) offsets are the indexed mode's point
        c = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=3,
                        indexed=True)
        c.seek_samples(14)
        np.testing.assert_array_equal(c.next()[0][:2], nxt[2:])

    def test_indexed_corpus_mode(self, tmp_path):
        path = str(tmp_path / "toks.bin")
        (np.arange(50_000) % 500).astype(np.uint16).tofile(path)
        a = TokenLoader(path, batch=2, seq=16, seed=1, indexed=True)
        x, y = a.next()
        np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
        b = TokenLoader(path, batch=2, seq=16, seed=1, indexed=True)
        np.testing.assert_array_equal(b.next()[0], x)

    def test_batch_loader_seek_matches_replay(self):
        a = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=9,
                        force_numpy=True)
        for _ in range(3):
            a.next()
        b = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=9,
                        force_numpy=True)
        b.seek_samples(12)
        assert b.samples_seen == 12
        np.testing.assert_array_equal(b.next()[0], a.next()[0])

    def test_native_loader_seek_replays(self):
        a = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=9)
        if a.backend != "native":
            pytest.skip("native loader unavailable")
        for _ in range(2):
            a.next()
        b = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=9)
        b.seek_samples(8)
        np.testing.assert_array_equal(b.next()[0], a.next()[0])

    def test_seek_guards(self):
        a = TokenLoader(None, batch=4, seq=16, vocab_size=64, seed=9,
                        force_numpy=True)
        a.next()
        with pytest.raises(ValueError, match="backwards"):
            a.seek_samples(0)
        with pytest.raises(ValueError, match="batch-aligned"):
            a.seek_samples(6)

    def test_data_offset_batches(self):
        info = {"data": {"samples_seen": 24, "global_batch": 8}}
        assert data_offset_batches(info, 8) == 3
        assert data_offset_batches(info, 4) == 6  # elastic: new batch size
        with pytest.raises(ValueError, match="not divisible"):
            data_offset_batches(info, 7)
        assert data_offset_batches({}, 8) is None  # legacy: no data meta


# ---------------------------------------------------------------------------
# chaos: NaN injection drives the detector + postmortem + recovery e2e
# ---------------------------------------------------------------------------

class TestChaosNanRecovery:
    def test_deterministic_schedule(self):
        a = Chaos(seed=11, nan_prob=0.5)
        b = Chaos(seed=11, nan_prob=0.5)
        pat_a = [a.fires("nan", s) for s in range(32)]
        pat_b = [b.fires("nan", s) for s in range(32)]
        assert pat_a == pat_b and any(pat_a) and not all(pat_a)
        assert [c.fires("nan", s) for c in [Chaos(seed=12, nan_prob=0.5)]
                for s in range(32)] != pat_a

    def test_nan_injection_detected_and_recovered(self, model, mesh4,
                                                  tmp_path):
        """The full loop: chaos NaNs a param -> the next step's health
        goes non-finite -> flight recorder arms -> manager snapshots a
        POSTMORTEM (resume chain untouched) -> recovery reloads the last
        good committed step and training continues finite."""
        d = str(tmp_path)
        telem = Telemetry(flight_steps=8)
        eng = Zero2(model, AdamW(lr=1e-3), mesh=mesh4, telemetry=telem)
        chaos = Chaos(nan_steps=(2,))
        ce = ChaosEngine(eng, chaos)
        mgr = CheckpointManager(d, every=2, engine=eng, telemetry=telem,
                                async_save=False)
        s = eng.init(jax.random.PRNGKey(0))
        for it in range(4):
            with telem.step(index=it):
                s, _ = ce.step(s, batch(it))
            mgr.maybe_save(s, it + 1,
                           data_meta={"samples_seen": (it + 1) * 8,
                                      "global_batch": 8, "seed": 0})
            if telem.flight_pending == "nonfinite" \
                    or mgr.last_reason == "anomaly:nonfinite":
                break
        # injected after step index 2 -> detected on step index 3
        assert mgr.last_reason == "anomaly:nonfinite"
        assert telem.counters["anomalies_nonfinite"].value == 1
        assert latest_step(d) == 2                     # last GOOD commit
        assert latest_step(os.path.join(d, "postmortem")) is not None

        good, info = elastic_load(d, eng)
        assert info["resumed_step"] == 2
        for leaf in jax.tree.leaves(good.params):
            assert bool(jnp.all(jnp.isfinite(leaf)))
        good, loss = eng.step(good, batch(2))
        assert np.isfinite(float(loss))

    def test_fault_records_validate_against_schema(self):
        from tiny_deepspeed_tpu.telemetry.schema import validate_record
        chaos = Chaos(nan_steps=(1,), sigterm_step=None)
        chaos.fires("nan", 1)
        chaos.record("ckpt_kill", path="/x", attempts=0)

        class Sink:
            recs = []

            def log_meta(self, kind, **fields):
                self.recs.append({"kind": kind, "ts": 0.0, **fields})

        sink = Sink()
        chaos.log_faults(sink)
        assert chaos.injected == []
        assert len(sink.recs) == 2
        for rec in sink.recs:
            assert validate_record(rec) == []


# ---------------------------------------------------------------------------
# killed-process restart: a REAL SIGKILL mid-commit, then a fresh process
# resumes from the last committed step (heavy: 3 subprocess JAX inits)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_real_process_kill_and_restart(tmp_path):
    import json
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "resilience_worker.py")
    d = str(tmp_path)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

    def run(mode, iters):
        return subprocess.run(
            [sys.executable, worker, mode, d, str(iters)],
            capture_output=True, text=True, timeout=300, env=env,
        )

    crash = run("crash", 4)
    assert crash.returncode == -signal.SIGKILL, (crash.returncode,
                                                 crash.stderr[-500:])
    # died between tmp-write and commit of step 4: partial on disk,
    # resume chain ends at the last COMMITTED step
    assert any(n.startswith(".tmp_step_00000004") for n in os.listdir(d))
    assert latest_step(d) == 2

    resumed = run("resume", 6)
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    rec = json.loads(resumed.stdout.strip().splitlines()[-1])
    assert rec["resumed"] == 2

    straight = run("straight", 6)
    assert straight.returncode == 0, straight.stderr[-2000:]
    ref = json.loads(straight.stdout.strip().splitlines()[-1])
    np.testing.assert_allclose(rec["losses"], ref["losses"][2:], rtol=1e-6)


# ---------------------------------------------------------------------------
# straggler mitigation: rebalance per-host data shards
# ---------------------------------------------------------------------------

class TestStragglerRebalance:
    def test_shares_exact_sum_and_monotonic(self):
        shares = rebalance_shares([0.1, 0.1, 0.3, 0.1], 64)
        assert sum(shares) == 64
        assert shares[2] < min(shares[0], shares[1], shares[3])
        assert all(s >= 1 for s in shares)
        # balanced hosts split evenly
        assert rebalance_shares([0.2] * 4, 64) == [16] * 4

    def test_min_share_and_guards(self):
        shares = rebalance_shares([0.001, 10.0], 8, min_share=2)
        assert shares[1] == 2 and sum(shares) == 8
        with pytest.raises(ValueError, match="min_share"):
            rebalance_shares([1.0, 1.0], 1, min_share=1)

    def test_hysteresis_fires_after_patience(self):
        telem = Telemetry(flight_steps=0)
        reb = ShardRebalancer(global_batch=32, threshold=0.3, patience=3,
                              telemetry=telem)
        skew = [0.1, 0.1, 0.1, 0.4]        # frac = (0.4-0.1)/0.4 = 0.75
        assert reb.observe(skew) is None
        assert reb.observe([0.1] * 4) is None   # streak broken
        assert reb.observe(skew) is None
        assert reb.observe(skew) is None
        shares = reb.observe(skew)              # 3rd consecutive -> fire
        assert shares is not None and sum(shares) == 32
        assert shares[3] < shares[0]
        assert telem.counters["straggler_rebalances"].value == 1
        assert reb.observe(skew) is None        # re-armed

    def test_wired_to_straggler_attribution(self):
        """End-to-end with the PR-5 gauges: a chaos-delayed host shows up
        in sample_stragglers' gathered walls, and the rebalancer acts on
        exactly that record's step_s_by_host."""
        chaos = Chaos(delay_steps=(0, 1, 2), delay_s=0.05)
        telem = Telemetry(flight_steps=0)
        walls = [0.01, 0.01, 0.01
                 + (chaos.delay_s if chaos.fires("delay", 0) else 0.0)]
        rec = telem.sample_stragglers(
            step_s=walls[0], allgather=lambda _: walls,
            quantity="host_prep_s",
        )
        assert rec["slowest_host"] == 2
        assert telem.gauges["straggler_frac"] > 0.5
        reb = ShardRebalancer(global_batch=24, threshold=0.3, patience=1)
        shares = reb.observe(rec["step_s_by_host"])
        assert shares is not None and sum(shares) == 24 and \
            shares[2] < shares[0]
