# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Checkpoint/resume: sharded save + restore into engine shardings; training
continues bit-exact after resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import AdamW, GPTConfig, GPT2Model, Zero2, Zero3
from tiny_deepspeed_tpu.utils import (
    latest_step, load_checkpoint, save_checkpoint,
)

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)


def batch(i):
    k = jax.random.split(jax.random.PRNGKey(100 + i), 2)
    return (jax.random.randint(k[0], (8, 32), 0, 128),
            jax.random.randint(k[1], (8, 32), 0, 128))


class TestCheckpoint:
    @pytest.mark.slow  # tier-1 budget: roundtrip + sharding-preserved
    # restore is pinned quick by test_resume_training_bit_exact and
    # test_resilience's elastic suite — full tier
    def test_save_restore_roundtrip_zero2(self, tmp_path):
        model = GPT2Model(TINY)
        eng = Zero2(model, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        state, _ = eng.step(state, batch(0))

        save_checkpoint(str(tmp_path), state, step=1)
        assert latest_step(str(tmp_path)) == 1
        restored = load_checkpoint(str(tmp_path), eng)

        for n in state.params:
            np.testing.assert_array_equal(
                np.asarray(state.params[n]), np.asarray(restored.params[n])
            )
        # restored optimizer state keeps the engine's ZeRO sharding
        m = restored.opt_state["state"]["h.mlp.fc.w"]["m"]
        shard = m.sharding.shard_shape(m.shape)
        assert np.prod(shard) * 8 == np.prod(m.shape)

    def test_resume_training_bit_exact(self, tmp_path):
        model = GPT2Model(TINY)
        eng = Zero3(model, AdamW(lr=1e-3))

        # uninterrupted: 4 steps
        s = eng.init(jax.random.PRNGKey(0))
        for i in range(4):
            s, loss_ref = eng.step(s, batch(i))

        # interrupted at 2, saved, resumed in a fresh engine
        s2 = eng.init(jax.random.PRNGKey(0))
        for i in range(2):
            s2, _ = eng.step(s2, batch(i))
        save_checkpoint(str(tmp_path), s2, step=2)

        eng2 = Zero3(model, AdamW(lr=1e-3))
        s3 = load_checkpoint(str(tmp_path), eng2)
        for i in range(2, 4):
            s3, loss_res = eng2.step(s3, batch(i))

        assert float(loss_ref) == float(loss_res)
        for n in s.params:
            np.testing.assert_array_equal(
                np.asarray(s.params[n]), np.asarray(s3.params[n])
            )

    def test_latest_step_empty(self, tmp_path):
        assert latest_step(str(tmp_path)) is None
        with pytest.raises(FileNotFoundError):
            load_checkpoint(str(tmp_path))

    @pytest.mark.slow  # tier-1 budget: three dropout-engine compiles;
    # resume bit-exactness stays quick (test_resume_training_bit_exact)
    # and the legacy dropout-base fill has its own quick test below
    def test_resume_preserves_dropout_stream(self, tmp_path):
        """The dropout base key rides the TrainState through a checkpoint:
        a restored state stepping on a FRESH engine (no init call) draws the
        ORIGINAL seed's mask stream — bit-exact with the uninterrupted run.
        (Round-3 advice: the base used to be a jit closure constant set only
        in init(), so resume-without-init replayed a hard-coded stream.)"""
        cfg = GPTConfig(
            block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
            compute_dtype=jnp.float32, dropout=0.2,
        )
        model = GPT2Model(cfg)
        eng = Zero2(model, AdamW(lr=1e-3))

        s = eng.init(jax.random.PRNGKey(7))
        assert s.dropout_base is not None
        for i in range(3):
            s, loss_ref = eng.step(s, batch(i))

        s2 = eng.init(jax.random.PRNGKey(7))
        s2, _ = eng.step(s2, batch(0))
        save_checkpoint(str(tmp_path), s2, step=1)

        eng2 = Zero2(GPT2Model(cfg), AdamW(lr=1e-3))  # no init() call
        s3 = load_checkpoint(str(tmp_path), eng2)
        for i in range(1, 3):
            s3, loss_res = eng2.step(s3, batch(i))
        assert float(loss_ref) == float(loss_res)

        # and two different seeds draw two different mask streams
        sA = eng.init(jax.random.PRNGKey(1))
        sB = eng.init(jax.random.PRNGKey(2))
        assert not np.array_equal(
            np.asarray(sA.dropout_base), np.asarray(sB.dropout_base)
        )

    def test_legacy_checkpoint_without_dropout_base_restores(self, tmp_path):
        """A checkpoint saved before the dropout base moved into TrainState
        (no dropout_base leaf) still restores into a dropout-active engine:
        the loader falls back to the legacy fixed base with a warning."""
        import dataclasses
        import warnings as _warnings

        cfg = GPTConfig(
            block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
            compute_dtype=jnp.float32, dropout=0.2,
        )
        eng = Zero2(GPT2Model(cfg), AdamW(lr=1e-3))
        s = eng.init(jax.random.PRNGKey(0))
        legacy = dataclasses.replace(s, dropout_base=None)  # old format
        save_checkpoint(str(tmp_path), legacy, step=1)

        with _warnings.catch_warnings(record=True) as w:
            _warnings.simplefilter("always")
            restored = load_checkpoint(str(tmp_path), eng)
        assert any("dropout_base" in str(x.message) for x in w)
        assert restored.dropout_base is not None
        restored, loss = eng.step(restored, batch(0))
        assert float(loss) > 0
