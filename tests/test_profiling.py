# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Profiling/metrics subsystem: timer, comm report, JSONL metrics."""

import json

import jax
import jax.numpy as jnp
import jaxlib.version
import pytest

from tiny_deepspeed_tpu import AdamW, DDP, GPTConfig, GPT2Model, Zero2, Zero3
from tiny_deepspeed_tpu.utils import (
    MetricsLogger, StepTimer, comm_report, device_sync,
)

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)


class TestStepTimer:
    def test_times_steps(self):
        model = GPT2Model(TINY)
        eng = DDP(model, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        idx = jnp.zeros((8, 32), jnp.int32)
        timer = StepTimer()
        for _ in range(3):
            with timer.step():
                state, loss = eng.step(state, (idx, idx))
                timer.observe(loss)
        assert len(timer.times) == 3
        assert timer.mean_s > 0

    def test_device_sync_returns_value(self):
        assert device_sync(jnp.full((4,), 7.0)) == 7.0


class TestCommReport:
    def test_stage_shapes(self):
        model = GPT2Model(TINY)
        rep0 = comm_report(DDP(model, AdamW(lr=1e-3)))
        rep2 = comm_report(Zero2(model, AdamW(lr=1e-3)))
        rep3 = comm_report(Zero3(model, AdamW(lr=1e-3)))
        # stage >= 2 accumulation reduce-scatters PER microbatch (TPU
        # topology measurement, PROFILE.md); stage <= 1 still syncs once
        rep2a = comm_report(Zero2(model, AdamW(lr=1e-3), accum_steps=4))
        assert rep2a["grad_reduce_scatter_bytes"] == \
            4 * rep2["grad_reduce_scatter_bytes"]
        rep0a = comm_report(DDP(model, AdamW(lr=1e-3), accum_steps=4))
        assert rep0a["grad_allreduce_bytes"] == rep0["grad_allreduce_bytes"]
        assert rep0["grad_allreduce_bytes"] > 0
        assert rep0["grad_reduce_scatter_bytes"] == 0
        assert rep2["grad_reduce_scatter_bytes"] > 0
        assert rep2["param_all_gather_bytes"] > 0
        assert rep3["zero3_layer_gather_bytes"] > 0
        assert rep3["param_all_gather_bytes"] == 0
        # DDP all-reduce is the "2g" of the reference comment ledger
        assert rep0["grad_allreduce_bytes"] == 2 * rep2["grad_reduce_scatter_bytes"]

    def test_wire_agenda_hops_modeled(self):
        """ISSUE 17: comm_report prices the composed ZeRO-3 tail release
        (fp32 transpose RS/AR vs the tail codec) and the hpZ secondary
        rebuild (fp32 leaves vs fp8 blocks + scales) as their own
        fields, joined into total_bytes_per_step."""
        model = GPT2Model(TINY)
        gran2 = {i: i // 4 for i in range(8)}
        kw = dict(gather_prefetch=2, grad_buckets=2, grad_comm="int8")
        rep_f = comm_report(Zero3(model, AdamW(lr=1e-3), **kw))
        rep_q = comm_report(Zero3(model, AdamW(lr=1e-3),
                                  grad_comm_tail="int8", **kw))
        assert rep_f["zero3_tail_release_bytes"] > 0
        assert rep_q["zero3_tail_release_bytes"] > 0
        # the codec'd tail models FEWER bytes than the fp32 release —
        # note the cuts differ: this model prices the codec's full
        # RS + AG round trip, while the zero3_tail_wire_bytes ledger
        # gauge (and the >= 3x pin in test_schedule.py) isolates the
        # reduce half, so the modeled ratio is ~1.8x, not 3.6x
        assert (rep_q["zero3_tail_release_bytes"]
                < rep_f["zero3_tail_release_bytes"])
        rep_h = comm_report(Zero3(model, AdamW(lr=1e-3), hpz=True,
                                  hpz_granule_of=gran2))
        rep_h8 = comm_report(Zero3(model, AdamW(lr=1e-3), hpz=True,
                                   hpz_granule_of=gran2,
                                   hpz_comm="fp8"))
        assert rep_h["hpz_rebuild_bytes"] > 0
        assert rep_h["hpz_rebuild_bytes"] >= 3 * rep_h8["hpz_rebuild_bytes"]
        # no hpz / stages < 3: the hops do not exist
        assert comm_report(Zero3(model, AdamW(lr=1e-3)))[
            "hpz_rebuild_bytes"] == 0.0
        assert comm_report(Zero2(model, AdamW(lr=1e-3)))[
            "zero3_tail_release_bytes"] == 0.0


# Known environment-dependent failure on this jax 0.4.37 / jaxlib 0.4.36
# XLA-CPU build: the SPMD partitioner hits "Involuntary full
# rematerialization" on the attention backward dot's resharding
# ({devices=[8,1,..]} -> {devices=[1,2,4,..]}) and emits extra all-gathers
# (~3.58 MB measured vs the 0.83 MB ring model), so the formula-vs-ledger
# agreement these tests pin cannot hold HERE.  strict=False: partitioners
# without the fallback (TPU, newer jaxlibs) pass and report xpass.
_SPMD_REMAT_XFAIL = pytest.mark.xfail(
    jaxlib.version.__version__ == "0.4.36",
    reason="env-dependent: this XLA-CPU partitioner's involuntary full "
           "rematerialization inflates the measured all-gather wire past "
           "the ring-model prediction", strict=False)


class TestCommReportVsCompiledHLO:
    """comm_report's ring formulas validated against the collective ledger
    parsed out of the COMPILED step (utils/hlo_comm.py) — the round-2
    verdict's "formula, not a measurement" gap.  Numbers and the CPU
    reduce-scatter caveat are written up in PROFILE.md."""

    CFG = GPTConfig(block_size=64, vocab_size=256, n_layer=4, n_head=2,
                    n_embd=64, compute_dtype=jnp.float32)

    def _ledger(self, eng_cls, cfg=None):
        from tiny_deepspeed_tpu.utils.hlo_comm import hlo_comm_report
        model = GPT2Model(cfg or self.CFG)
        eng = eng_cls(model, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (16, 64), 0, 256)
        led = hlo_comm_report(eng, state, (idx, idx))
        assert not led["unresolved_loops"], led["unresolved_loops"]
        assert not led["unresolved_groups"], led["unresolved_groups"]
        return comm_report(eng), led

    @pytest.mark.slow  # tier-1 budget (scripts/tier1_times.py): the
    # zero1/zero2/zero3 rows below pin the same ring model across
    # harder layouts; the pure all-reduce row runs in the full tier
    def test_ddp_allreduce_matches(self):
        rep, led = self._ledger(DDP)
        # one variadic grad all-reduce; payload == param bytes (+ the f32
        # loss-mean scalar), wire == the predicted 2g(n-1)/n
        assert abs(led["payload_bytes"]["all-reduce"]
                   - rep["param_bytes"]) <= 64
        assert abs(led["wire_bytes"]["all-reduce"]
                   - rep["grad_allreduce_bytes"]) <= 128
        assert "all-gather" not in led["payload_bytes"]

    @_SPMD_REMAT_XFAIL
    def test_zero1_gather_and_allreduce_match(self):
        from tiny_deepspeed_tpu import Zero1
        rep, led = self._ledger(Zero1)
        assert abs(led["wire_bytes"]["all-gather"]
                   - rep["param_all_gather_bytes"]) <= 128
        assert abs(led["wire_bytes"]["all-reduce"]
                   - rep["grad_allreduce_bytes"]) <= 128

    @_SPMD_REMAT_XFAIL
    def test_zero2_grads_between_rs_and_ar(self):
        rep, led = self._ledger(Zero2)
        # param re-gather exactly as predicted
        assert abs(led["wire_bytes"]["all-gather"]
                   - rep["param_all_gather_bytes"]) <= 128
        # grads: the constraint's INTENT is a reduce-scatter (g(n-1)/n);
        # XLA's CPU partitioner emits all-reduce + slice (2x).  Pin the
        # window so a regression to anything worse still fails.
        grad_wire = (led["wire_bytes"].get("reduce-scatter", 0.0)
                     + led["wire_bytes"].get("all-reduce", 0.0))
        lo = rep["grad_reduce_scatter_bytes"]
        assert lo - 128 <= grad_wire <= 2 * lo + 256, (grad_wire, lo)

    def test_trip_count_prefers_root_compare_operand(self):
        """Round-3 advice: an unrelated larger constant in the while
        condition (e.g. a clamp bound) must not inflate the loop
        multiplier.  The bound is the ROOT compare's constant operand;
        conditions where no operand resolves and constants disagree are
        flagged unresolved, not silently maxed."""
        from tiny_deepspeed_tpu.utils.hlo_comm import _trip_count

        cond = [
            "  %c4 = s32[] constant(4)",
            "  %c99 = s32[] constant(99)",  # unrelated clamp bound
            "  %iv = s32[] get-tuple-element(%arg), index=0",
            "  %clamped = s32[] minimum(%iv, %c99)",
            "  ROOT %cmp = pred[] compare(s32[] %iv, s32[] %c4),"
            " direction=LT",
        ]
        assert _trip_count(cond) == (4, True)

        # TPU print format: layout annotations on constants AND compare
        # operands ("{:T(128)}" contains parens — a first-')' capture
        # truncates mid-annotation and resolves nothing)
        tpu_cond = [
            "  %c4 = s32[]{:T(128)} constant(4)",
            "  %c99 = s32[]{:T(128)} constant(99)",
            "  %iv = s32[]{:T(128)} get-tuple-element(%arg), index=0",
            "  ROOT %cmp = pred[]{:T(256)} compare(s32[]{:T(128)} %iv,"
            " s32[]{:T(128)} %c4), direction=LT, metadata={op_name=\"x\"}",
        ]
        assert _trip_count(tpu_cond) == (4, True)

        ambiguous = [
            "  %c4 = s32[] constant(4)",
            "  %c99 = s32[] constant(99)",
            "  ROOT %cmp = pred[] compare(s32[] %a, s32[] %b),"
            " direction=LT",
        ]
        trips, resolved = _trip_count(ambiguous)
        assert not resolved

        # ROOT compare with a DYNAMIC bound: the lone clamp constant must
        # not be promoted to a trip count (flagged unresolved instead)
        dynamic = [
            "  %c99 = s32[] constant(99)",
            "  %bound = s32[] get-tuple-element(%arg), index=1",
            "  ROOT %cmp = pred[] compare(%iv, %bound), direction=LT",
        ]
        trips, resolved = _trip_count(dynamic)
        assert not resolved

        # ROOT compare takes precedence over stray compares BOTH ways:
        # a resolved ROOT bound ignores a constant side-compare, and a
        # dynamic ROOT bound is NOT resolved by one
        stray = [
            "  %c4 = s32[] constant(4)",
            "  %c99 = s32[] constant(99)",
            "  %flagcmp = pred[] compare(%x, %c99), direction=LT",
            "  ROOT %cmp = pred[] compare(%iv, %c4), direction=LT",
        ]
        assert _trip_count(stray) == (4, True)
        stray_dyn = [
            "  %c99 = s32[] constant(99)",
            "  %flagcmp = pred[] compare(%x, %c99), direction=LT",
            "  ROOT %cmp = pred[] compare(%iv, %bound), direction=LT",
        ]
        trips, resolved = _trip_count(stray_dyn)
        assert not resolved

        # compound condition: the compare feeds a ROOT `and` — the bound
        # constant must still resolve via the non-ROOT compare, and a
        # dynamic-bound variant must stay unresolved despite the clamp
        compound = [
            "  %c4 = s32[] constant(4)",
            "  %cmp = pred[] compare(%iv, %c4), direction=LT",
            "  ROOT %and = pred[] and(%cmp, %flag)",
        ]
        assert _trip_count(compound) == (4, True)
        compound_dyn = [
            "  %c99 = s32[] constant(99)",
            "  %cmp = pred[] compare(%iv, %bound), direction=LT",
            "  ROOT %and = pred[] and(%cmp, %flag)",
        ]
        trips, resolved = _trip_count(compound_dyn)
        assert not resolved

        # no ROOT compare found at all: agreeing constants still resolve
        agreeing = [
            "  %c8 = s32[] constant(8)",
            "  ROOT %cmp = pred[] unusual-op(s32[] %a, s32[] %b)",
        ]
        assert _trip_count(agreeing) == (8, True)

    @_SPMD_REMAT_XFAIL
    def test_zero3_layer_gathers_match(self):
        rep, led = self._ledger(Zero3)
        # per-layer gathers: 2x block params (fwd + remat bwd) + 1x
        # non-block, compute dtype — the ledger multiplies the scan body
        # by its trip count, so agreement here validates both sides
        assert abs(led["wire_bytes"]["all-gather"]
                   - rep["zero3_layer_gather_bytes"]) \
            <= 0.1 * rep["zero3_layer_gather_bytes"]

    @pytest.mark.xfail(
        jaxlib.version.__version__ == "0.4.36",
        reason="env-dependent: this jaxlib 0.4.36 XLA-CPU backend cannot "
               "compile the pipeline step at all (UNIMPLEMENTED: "
               "PartitionId instruction is not supported for SPMD "
               "partitioning)",
        strict=False)
    def test_pipeline_ppermute_counts(self):
        """Cross-check the ledger's loop multiplication on a different
        collective/loop structure: the GPipe tick scan runs M+S-1 ticks
        with one activation ppermute per tick (forward), and autodiff's
        transposed scan adds the same count backward."""
        from tiny_deepspeed_tpu import Zero1
        from tiny_deepspeed_tpu.utils.hlo_comm import hlo_comm_report
        model = GPT2Model(self.CFG)
        s_stages, m_micro = 4, 8
        eng = Zero1(model, AdamW(lr=1e-3), pipeline_parallel=s_stages,
                    pipeline_microbatches=m_micro)
        state = eng.init(jax.random.PRNGKey(0))
        idx = jax.random.randint(jax.random.PRNGKey(1), (16, 64), 0, 256)
        led = hlo_comm_report(eng, state, (idx, idx))
        ticks = m_micro + s_stages - 1
        # fwd scan: 1 ppermute/tick; bwd transposed scan: 1 more.  XLA may
        # emit the pair fused or cloned, so pin a window, not equality.
        n = led["count"].get("collective-permute", 0)
        assert 2 * ticks <= n <= 3 * ticks, (n, ticks)

    @pytest.mark.slow  # tier-1 budget: fp8 gather wire is also pinned
    # in test_zero3_gather_prefetch + the slow test_fp8_gather suite
    def test_zero3_fp8_gather_priced_from_stacked_dtypes(self):
        import dataclasses
        q = dataclasses.replace(self.CFG, gather_quant="fp8")
        rep_f32, led_f32 = self._ledger(Zero3)
        rep_q, led_q = self._ledger(Zero3, cfg=q)
        # the formula prices quantized block gathers at the stacked tree's
        # own dtypes (f8 + f32 scales), so the prediction drops well below
        # the f32 one — that is the feature's INTENT
        assert rep_q["zero3_layer_gather_bytes"] \
            < 0.5 * rep_f32["zero3_layer_gather_bytes"]
        # REALITY on the CPU backend (measured round 3, confirming the
        # round-2 verdict's suspicion): the intent does NOT materialize —
        # f8 collectives upcast to f16 and several remat-backward gathers
        # stay full precision, so the compiled program moves MORE than the
        # f32 config (observed ~1.34x).  Pin the window so (a) this honest
        # finding stays recorded and (b) a future regression past 1.6x
        # still fails.  The TPU partitioner may do better; until a
        # multi-chip TPU HLO exists this is the measured truth.
        assert led_q["wire_bytes"]["all-gather"] \
            > rep_q["zero3_layer_gather_bytes"]
        assert led_q["wire_bytes"]["all-gather"] \
            <= 1.6 * led_f32["wire_bytes"]["all-gather"]


class TestMetricsLogger:
    def test_jsonl_output(self, tmp_path, capsys):
        path = tmp_path / "metrics.jsonl"
        logger = MetricsLogger(str(path), stdout=True)
        logger.log(0, loss=1.25, tokens_per_sec=1000.0)
        logger.log(1, loss=1.20, tokens_per_sec=1100.0)
        logger.close()
        lines = [json.loads(x) for x in path.read_text().splitlines()]
        assert [x["step"] for x in lines] == [0, 1]
        assert lines[0]["loss"] == 1.25
        out = capsys.readouterr().out
        assert "step     0" in out and "loss 1.2500" in out
