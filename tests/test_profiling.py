# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Profiling/metrics subsystem: timer, comm report, JSONL metrics."""

import json

import jax
import jax.numpy as jnp

from tiny_deepspeed_tpu import AdamW, DDP, GPTConfig, GPT2Model, Zero2, Zero3
from tiny_deepspeed_tpu.utils import (
    MetricsLogger, StepTimer, comm_report, device_sync,
)

TINY = GPTConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
    compute_dtype=jnp.float32,
)


class TestStepTimer:
    def test_times_steps(self):
        model = GPT2Model(TINY)
        eng = DDP(model, AdamW(lr=1e-3))
        state = eng.init(jax.random.PRNGKey(0))
        idx = jnp.zeros((8, 32), jnp.int32)
        timer = StepTimer()
        for _ in range(3):
            with timer.step():
                state, loss = eng.step(state, (idx, idx))
                timer.observe(loss)
        assert len(timer.times) == 3
        assert timer.mean_s > 0

    def test_device_sync_returns_value(self):
        assert device_sync(jnp.full((4,), 7.0)) == 7.0


class TestCommReport:
    def test_stage_shapes(self):
        model = GPT2Model(TINY)
        rep0 = comm_report(DDP(model, AdamW(lr=1e-3)))
        rep2 = comm_report(Zero2(model, AdamW(lr=1e-3)))
        rep3 = comm_report(Zero3(model, AdamW(lr=1e-3)))
        assert rep0["grad_allreduce_bytes"] > 0
        assert rep0["grad_reduce_scatter_bytes"] == 0
        assert rep2["grad_reduce_scatter_bytes"] > 0
        assert rep2["param_all_gather_bytes"] > 0
        assert rep3["zero3_layer_gather_bytes"] > 0
        assert rep3["param_all_gather_bytes"] == 0
        # DDP all-reduce is the "2g" of the reference comment ledger
        assert rep0["grad_allreduce_bytes"] == 2 * rep2["grad_reduce_scatter_bytes"]


class TestMetricsLogger:
    def test_jsonl_output(self, tmp_path, capsys):
        path = tmp_path / "metrics.jsonl"
        logger = MetricsLogger(str(path), stdout=True)
        logger.log(0, loss=1.25, tokens_per_sec=1000.0)
        logger.log(1, loss=1.20, tokens_per_sec=1100.0)
        logger.close()
        lines = [json.loads(x) for x in path.read_text().splitlines()]
        assert [x["step"] for x in lines] == [0, 1]
        assert lines[0]["loss"] == 1.25
        out = capsys.readouterr().out
        assert "step     0" in out and "loss 1.2500" in out
