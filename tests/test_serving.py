# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Serving tier: paged KV pool, continuous batching, quantized cache,
and the fault-tolerance layer (SLOs, decode-health guard, journal).

Acceptance pins (ISSUE 7):
  * paged decode is token-exact with `GPT2Model.generate` greedy, per
    request, under concurrency and staggered admission;
  * pool accounting is exact at every scheduler tick (blocks-in-use ==
    sum of active block-table lengths) and freed blocks are reused
    deterministically without corrupting neighbors;
  * int8/fp8 cache blocks quarter the pool's resting KV bytes vs f32
    (asserted from array dtypes/shapes) within decode-parity tolerance;
  * importing/instantiating the serving package leaves the TRAINING
    step's HLO byte-identical (subprocess-pinned, fresh import order);
  * the Poisson soak (slow tier): >= 4 concurrent requests beat the
    same trace served one-at-a-time through `generate`.

Acceptance pins (ISSUE 8, robustness):
  * terminal statuses are exact and exclusive (ok/shed/expired/failed),
    each with its JSONL `request` record;
  * a NaN-poisoned slot is quarantined WITHOUT taking the batch down —
    neighbors stay token-exact — and every freed block returns to the
    pool exactly once under a quarantine storm;
  * the watchdog warm-restarts on K consecutive poisoned ticks or a
    tick exception, and the re-queued requests continue token-exact;
  * kill-mid-trace (slow tier): SIGKILL the serving process, recover a
    fresh engine from the journal, final sequences identical to the
    uninterrupted run;
  * temperature > 0 preemption resume is deterministic under the
    (request seed, position) sampling keys.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import GPTConfig, GPT2Model

# small-and-fast config (test_model.py's TestKVCacheDecode family): XLA-CPU
# compiles of the serving programs dominate this module's budget
CFG = dict(block_size=64, vocab_size=128, n_layer=2, n_head=2,
           n_embd=32, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return GPT2Model(GPTConfig(**CFG))


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0))


def _prompt(seed, n, vocab=128):
    return np.asarray(
        jax.random.randint(jax.random.PRNGKey(seed), (n,), 0, vocab),
        np.int32,
    ).tolist()


def _ref_tokens(model, params, prompt, new):
    out = model.generate(
        params, np.asarray(prompt, np.int32)[None, :], new,
        temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):]


def _serve_config(**kw):
    from tiny_deepspeed_tpu.serving import ServeConfig
    kw.setdefault("max_active", 3)
    kw.setdefault("num_blocks", 24)
    kw.setdefault("block_tokens", 8)
    return ServeConfig(**kw)


def _assert_accounting(eng):
    used = sum(len(t) for t in eng.active_block_tables().values())
    assert used == eng.pool.blocks_in_use, (
        f"pool accounting drift: tables hold {used}, pool reports "
        f"{eng.pool.blocks_in_use}"
    )


class TestSamplingCore:
    """ONE sampling core (models/sampling.py) for generate + serving."""

    def test_greedy_is_argmax_and_ignores_key(self):
        from tiny_deepspeed_tpu.models.sampling import sample_logits
        logit = jnp.asarray(np.random.default_rng(0).normal(
            size=(3, 16)).astype(np.float32))
        a = sample_logits(logit, jax.random.PRNGKey(0), 0.0, None)
        b = sample_logits(logit, jax.random.PRNGKey(7), 0.0, None)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            np.asarray(a), np.argmax(np.asarray(logit), -1))

    def test_top_k_restricts_support(self):
        from tiny_deepspeed_tpu.models.sampling import sample_logits
        logit = jnp.asarray(
            np.arange(12, dtype=np.float32)[None, :])  # top-2 = {10, 11}
        for seed in range(8):
            t = int(sample_logits(
                logit, jax.random.PRNGKey(seed), 1.0, 2)[0])
            assert t in (10, 11)

    def test_generate_sample_delegates_to_core(self, monkeypatch):
        """GPT2Model._sample IS the shared core, not a drifted copy."""
        from tiny_deepspeed_tpu.models import sampling
        calls = {}
        orig = sampling.sample_logits

        def spy(logit, key, temperature, top_k=None):
            calls["hit"] = True
            return orig(logit, key, temperature, top_k)

        monkeypatch.setattr(sampling, "sample_logits", spy)
        GPT2Model._sample(jnp.zeros((1, 4)), jax.random.PRNGKey(0),
                          0.0, None)
        assert calls.get("hit")


class TestContinuousBatching:
    def test_staggered_greedy_parity_and_exact_accounting(
            self, model, params):
        """Requests admitted and evicted at DIFFERENT ticks (two shape
        groups, second wave submitted mid-flight) each reproduce their
        `generate` tokens exactly, with pool accounting exact at every
        tick — the continuous-batching core contract."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params, _serve_config())
        specs = [(1, 7, 10), (2, 13, 6)]
        reqs = [eng.submit(_prompt(s, n), new) for s, n, new in specs]
        for _ in range(3):
            eng.tick()
            _assert_accounting(eng)
        late = [(3, 7, 10), (4, 13, 6)]  # same shapes: no new compiles
        reqs += [eng.submit(_prompt(s, n), new) for s, n, new in late]
        ticks = 0
        while eng.queue_depth or eng.n_active:
            eng.tick()
            _assert_accounting(eng)
            ticks += 1
            assert ticks < 100
        assert eng.pool.blocks_in_use == 0
        for r, (s, n, new) in zip(reqs, specs + late):
            assert len(r.tokens) == new
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, new),
                err_msg=f"request {r.id} diverged from generate()",
            )
            assert r.state == "done" and r.finish_reason == "length"

    @pytest.mark.slow  # redundant in tier-1 since ISSUE 13: the
    # prefix-cache choreography (test_serving_prefix.py::
    # TestPrefixServing) admits a COLD boundary-length prompt through
    # this same plain full-prefill path (its first request, p == 2*bt)
    # and pins token parity — the boundary +1-block rule stays quick
    # there; this dedicated two-prompt variant keeps the coverage in
    # the slow tier
    def test_block_boundary_prompt_parity(self, model, params):
        """Prompt length exactly on a block boundary (p % block_tokens
        == 0): the first decode write lands at position p, i.e. in a
        block BEYOND ceil(p/bt) — admission must allocate it up front
        or that K/V silently lands in the scratch block and every later
        token attends to a hole.  Token-exact parity pins it."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params, _serve_config())
        specs = [(11, 8, 6), (12, 16, 6)]  # p == bt and p == 2*bt
        reqs = [eng.submit(_prompt(s, n), new) for s, n, new in specs]
        ticks = 0
        while eng.queue_depth or eng.n_active:
            eng.tick()
            _assert_accounting(eng)
            ticks += 1
            assert ticks < 50
        for r, (s, n, new) in zip(reqs, specs):
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, new),
                err_msg=f"boundary request {r.id} diverged",
            )

    @pytest.mark.slow  # redundant in tier-1 since ISSUE 13: realloc
    # cleanliness is now exercised HARDER quick by the refcounted-pool
    # tests (test_serving_prefix.py) — LIFO realloc determinism is
    # pinned at the pool level, and the prefix choreography reuses
    # tree-evicted blocks mid-trace with per-tick refcount accounting
    # + token parity; this engine-level variant keeps the
    # evictee-block-overlap assertion in the slow tier
    def test_block_realloc_after_eviction_is_clean(self, model, params):
        """A request admitted AFTER an eviction reuses the evictee's
        freed blocks (the free list is LIFO, so they come back first)
        without corrupting the still-active neighbor."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        # 2 slots: r2 must WAIT until short-lived r0 finishes; r0's
        # blocks are the most recently freed when r2 admits
        eng = ServingEngine(model, params,
                            _serve_config(max_active=2, num_blocks=6))
        r0 = eng.submit(_prompt(1, 7), 6)    # finishes first
        r1 = eng.submit(_prompt(2, 13), 10)  # active throughout
        eng.tick()
        r0_blocks = set(eng.active_block_tables()[r0.id])
        r2 = eng.submit(_prompt(3, 13), 6)
        ticks = 0
        r2_blocks = None
        while eng.queue_depth or eng.n_active:
            eng.tick()
            _assert_accounting(eng)
            if r2.state == "active" and r2_blocks is None:
                r2_blocks = set(eng.active_block_tables()[r2.id])
                assert r0.done  # admission had to wait for the eviction
                assert r1.state == "active"  # the neighbor lives on
            ticks += 1
            assert ticks < 100
        assert r2_blocks is not None and r2_blocks & r0_blocks, (
            "r2 was expected to reuse blocks freed by r0"
        )
        for r, new in ((r0, 6), (r1, 10), (r2, 6)):
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, new),
                err_msg=f"request {r.id} corrupted across realloc",
            )

    def test_refusals(self, model, params):
        from tiny_deepspeed_tpu import MoEConfig, MoEGPT
        from tiny_deepspeed_tpu.serving import ServingEngine
        with pytest.raises(ValueError, match="paged_decode_capable"):
            ServingEngine(MoEGPT(MoEConfig(n_expert=2, **CFG)), params,
                          _serve_config())
        with pytest.raises(ValueError, match="must divide"):
            ServingEngine(model, params, _serve_config(block_tokens=7))
        with pytest.raises(ValueError, match="KV-cache quant"):
            ServingEngine(model, params, _serve_config(quant="int4"))
        eng = ServingEngine(model, params, _serve_config(num_blocks=2))
        with pytest.raises(ValueError, match="blocks"):
            eng.submit(_prompt(1, 30), 30)  # can never fit the pool
        with pytest.raises(ValueError, match="block_size"):
            eng.submit(_prompt(1, 60), 30)  # exceeds the model context


class TestQuantizedCache:
    def test_pool_bytes_quartered_from_dtypes(self):
        """int8/fp8 pools rest at 1 byte/element vs the f32 baseline's 4
        — asserted from the device arrays' dtypes and shapes, not a
        model.  (On a bf16-compute config the same blocks HALVE.)"""
        from tiny_deepspeed_tpu.serving.pool import PagedKVPool
        kw = dict(n_layer=2, kv_heads=2, head_dim=16, num_blocks=8,
                  block_tokens=8)
        base = PagedKVPool(dtype=jnp.float32, **kw).kv_bytes()
        half = PagedKVPool(dtype=jnp.bfloat16, **kw).kv_bytes()
        assert half["kv_block_bytes"] * 2 == base["kv_block_bytes"]
        for quant, dt in (("int8", jnp.int8), ("fp8", jnp.float8_e4m3fn)):
            q = PagedKVPool(dtype=jnp.float32, quant=quant, **kw)
            b = q.kv_bytes()
            assert jnp.dtype(q.view.k.dtype) == jnp.dtype(dt)
            assert b["itemsize"] == 1
            assert b["kv_block_bytes"] * 4 == base["kv_block_bytes"]
            assert b["scale_bytes"] > 0  # f32 absmax per head vector

    def test_codec_roundtrip_error_bounded(self):
        """paged_append -> paged_panel through an int8 pool stays within
        the blockwise-absmax codec's per-element bound (scale/2, scale =
        vector absmax / 127) — the grad-comm machinery reused verbatim."""
        from tiny_deepspeed_tpu.serving.pool import (
            PagedKVPool, page_ref, paged_append, paged_panel,
        )
        dh, kvh, s = 16, 2, 3
        pool = PagedKVPool(n_layer=1, kv_heads=kvh, head_dim=dh,
                           num_blocks=4, block_tokens=4,
                           dtype=jnp.float32, quant="int8")
        rng = np.random.default_rng(0)
        k = rng.normal(size=(s, kvh, dh)).astype(np.float32)
        v = rng.normal(size=(s, kvh, dh)).astype(np.float32)
        tables = np.asarray([[1, 0], [2, 0], [3, 0]], np.int32)
        ref = page_ref(jnp.asarray(tables), jnp.zeros((s,), jnp.int32), 4)
        view = paged_append(pool.view, jnp.asarray(k), jnp.asarray(v), 0,
                            ref)
        ck, cv = paged_panel(view, 0, ref, jnp.float32)
        got_k = np.asarray(ck)[:, :, 0, :]  # position 0 of each panel
        got_v = np.asarray(cv)[:, :, 0, :]
        for got, ref_a in ((got_k, k), (got_v, v)):
            bound = np.abs(ref_a).max(-1, keepdims=True) / 127.0 * 0.5001
            assert (np.abs(got - ref_a) <= bound + 1e-7).all()

    # fp8 demoted to slow (ISSUE-12 tier-1 budget): the fp8 codec is
    # primitive-pinned by the quick roundtrip-bound test and the decode
    # integration path is identical per mode — the int8 case keeps the
    # quantized-decode wiring quick
    @pytest.mark.parametrize("quant", [
        "int8", pytest.param("fp8", marks=pytest.mark.slow)])
    def test_quantized_decode_parity_tolerance(self, model, params,
                                               quant):
        """Quantized-cache greedy decode tracks the f32 reference: the
        prefill/first token is exact (full-precision forward), and the
        decode logits stay close enough that tokens rarely flip at this
        scale."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params,
                            _serve_config(quant=quant, max_active=2))
        specs = [(1, 7, 8), (2, 13, 8)]
        reqs = [eng.submit(_prompt(s, n), new) for s, n, new in specs]
        eng.drain(max_ticks=200)
        for r, (s, n, new) in zip(reqs, specs):
            ref = _ref_tokens(model, params, r.prompt, new)
            assert len(r.tokens) == new
            assert r.tokens[0] == ref[0], "prefill token must be exact"
            agree = float((np.asarray(r.tokens) == ref).mean())
            assert agree >= 0.75, (
                f"{quant} cache diverged: {agree:.2f} agreement"
            )


class TestCacheDtypeKnob:
    def test_bf16_cache_greedy_parity_with_full_forward(self):
        """cache_dtype="bf16" on an f32-compute config: cached greedy
        decode still equals the uncached full-forward tokens (seed-
        pinned) — retiring gpt2.py's '(future-knob) cache dtype'."""
        m = GPT2Model(GPTConfig(cache_dtype="bf16", **CFG))
        p = m.init(jax.random.PRNGKey(0))
        idx = np.asarray(_prompt(5, 7), np.int32)[None, :]
        a = m.generate(p, idx, 10, temperature=0.0, use_cache=True)
        b = m.generate(p, idx, 10, temperature=0.0, use_cache=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and the cache really rests narrower: the serving pool derives
        # its dtype from the same resolver
        from tiny_deepspeed_tpu.models.gpt2 import resolved_cache_dtype
        assert resolved_cache_dtype(m.config) == jnp.bfloat16

    def test_resolver(self):
        from tiny_deepspeed_tpu.models.gpt2 import resolved_cache_dtype
        assert resolved_cache_dtype(GPTConfig(**CFG)) == jnp.float32
        assert resolved_cache_dtype(
            GPTConfig(cache_dtype=jnp.float16, **CFG)) == jnp.float16
        with pytest.raises(ValueError, match="cache_dtype"):
            resolved_cache_dtype(GPTConfig(cache_dtype="int8", **CFG))


class TestServingTelemetry:
    @pytest.mark.slow  # redundant in tier-1 since ISSUE 13: the
    # prefix-cache choreography (test_serving_prefix.py) validates a
    # full engine record file against the schema (superset: v9 tenant/
    # prefix fields + gauges), and test_serve_observability pins the
    # plain request-record field surface quick; the gauge registry/
    # GAUGES cross-check stays quick via the repo-hygiene grep guard
    def test_gauges_counters_and_request_records(self, model, params,
                                                 tmp_path):
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.telemetry import Telemetry
        from tiny_deepspeed_tpu.telemetry import schema
        from tiny_deepspeed_tpu.utils.profiling import MetricsLogger
        tel = Telemetry()
        path = str(tmp_path / "serve.jsonl")
        with MetricsLogger(path, stdout=False) as ml:
            ml.log_meta(schema_version=schema.SCHEMA_VERSION,
                        engine="serve:test")
            eng = ServingEngine(model, params, _serve_config(),
                                telemetry=tel, logger=ml)
            reqs = [eng.submit(_prompt(1, 7), 10),
                    eng.submit(_prompt(2, 7), 10)]
            eng.drain(max_ticks=200)
            tel.flush(ml)
        assert all(r.done for r in reqs)
        g = tel.gauges
        assert g["serve_batch_occupancy"] == 0.0  # drained
        assert g["serve_pool_utilization"] == 0.0
        assert g["serve_queue_depth"] == 0.0
        assert g["serve_eviction_rate"] > 0.0
        assert tel.counters["serve_tokens"].value == 20
        assert tel.counters["serve_evictions"].value == 2
        # every serve gauge name is documented (the schema drift guard
        # enforces the same via grep; this pins the registry side)
        for name in g:
            assert name in schema.GAUGES
        counts, errs = schema.validate_file(path)
        assert not errs, errs
        with open(path) as f:
            kinds = [json.loads(ln).get("kind") for ln in f]
        assert kinds.count("request") == 2

    @pytest.mark.slow  # redundant in tier-1 since ISSUE 13: the
    # tenant-isolation pin (test_serving_prefix.py) drives the SAME
    # run_trace closed-loop path with richer asserts (per-tenant
    # aggregates + status counts), and the staggered-parity test keeps
    # plain-engine scheduling quick; this smoke keeps the poisson_trace
    # shape assertions in the slow tier
    def test_driver_closed_loop_smoke(self, model, params):
        """poisson_trace + run_trace (the serve_bench/BENCH_SERVE code
        path), closed-loop so the smoke never sleeps."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.serving.driver import (
            poisson_trace, run_trace,
        )
        trace = poisson_trace(3, rate_rps=None, prompt_lens=[7, 13],
                              max_new_tokens=5, vocab_size=128, seed=0)
        assert [a.at_s for a in trace] == [0.0, 0.0, 0.0]
        eng = ServingEngine(model, params, _serve_config())
        res = run_trace(eng, trace, realtime=False)
        assert res["tokens"] == 15 and res["tokens_per_s"] > 0
        assert len(res["outputs"]) == 3
        assert set(res["token_latency"]) == {"p50_ms", "p99_ms",
                                             "mean_ms"}
        assert 0 < res["mean_occupancy"] <= 1.0


class TestServeSLOs:
    """Request deadlines + load shedding: every terminal outcome is a
    distinct status and nothing queues unboundedly."""

    def test_submit_sheds_on_queue_watermark(self, model, params,
                                             tmp_path):
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.telemetry import schema
        from tiny_deepspeed_tpu.utils.profiling import MetricsLogger
        path = str(tmp_path / "shed.jsonl")
        with MetricsLogger(path, stdout=False) as ml:
            eng = ServingEngine(model, params,
                                _serve_config(max_queue=2), logger=ml)
            reqs = [eng.submit(_prompt(s, 7), 4) for s in range(5)]
        shed = [r for r in reqs if r.status == "shed"]
        # 5 submitted, 0 active yet, watermark 2: the last 3 shed at the
        # door with a terminal record, never queued
        assert len(shed) == 3 and eng.queue_depth == 2
        assert all(r.done and r.finish_reason == "shed:queue_watermark"
                   and not r.tokens for r in shed)
        counts, errs = schema.validate_file(path)
        assert not errs, errs
        with open(path) as f:
            recs = [json.loads(ln) for ln in f]
        assert [r["status"] for r in recs
                if r.get("kind") == "request"] == ["shed"] * 3

    def test_submit_sheds_on_pool_pressure(self, model, params):
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(
            model, params,
            _serve_config(max_active=1, num_blocks=4,
                          shed_pool_util=0.5))
        r0 = eng.submit(_prompt(1, 13), 10)  # holds >= 2/4 blocks
        eng.tick()
        r1 = eng.submit(_prompt(2, 7), 4)    # queued (backlog forms)
        r2 = eng.submit(_prompt(3, 7), 4)    # pool full + backlog: shed
        assert r1.status is None and r2.status == "shed"
        assert r2.finish_reason == "shed:pool_watermark"
        eng.drain(max_ticks=200)
        assert r0.status == "ok" and r1.status == "ok"

    def test_active_deadline_expiry_evicts(self, model, params):
        """An active request past its deadline is evicted as `expired`
        (partial tokens kept, blocks freed); its neighbor without a
        deadline is untouched and stays token-exact."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params, _serve_config(max_active=2))
        ra = eng.submit(_prompt(1, 7), 12, deadline_s=60.0)
        rb = eng.submit(_prompt(2, 7), 12)
        eng.tick()
        assert ra.state == "active"
        ra.t_arrival -= 120.0  # move its deadline into the past
        eng.tick()
        _assert_accounting(eng)
        assert ra.status == "expired" and ra.finish_reason == "deadline"
        assert 0 < len(ra.tokens) < 12  # partial delivery
        eng.drain(max_ticks=100)
        assert rb.status == "ok"
        np.testing.assert_array_equal(
            np.asarray(rb.tokens), _ref_tokens(model, params, rb.prompt,
                                               12),
            err_msg="neighbor diverged across an expiry eviction",
        )
        assert eng.pool.blocks_in_use == 0

    def test_queue_shed_on_unmeetable_deadline(self, model, params):
        """A queued request whose deadline cannot be met at the
        measured inter-token rate is shed BEFORE wasting a prefill.
        The price comes from the engine's decode-wall history, so warm
        it first; the overdue case needs no history at all."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params, _serve_config(max_active=1))
        warm = eng.submit(_prompt(1, 7), 8)
        eng.drain(max_ticks=100)  # 8 decode walls measured
        assert warm.status == "ok" and eng._gap_p50() is not None
        holder = eng.submit(_prompt(2, 7), 12)   # occupies the 1 slot
        eng.tick()
        # queued behind it: needs 30 tokens but the deadline is one
        # measured tick wide — unmeetable at any realistic rate
        tight = eng.submit(_prompt(3, 7), 30,
                           deadline_s=eng._gap_p50() * 1.0)
        eng.tick()
        assert tight.status == "shed"
        assert tight.finish_reason.startswith("shed:deadline")
        assert not tight.tokens  # never admitted, no prefill paid
        eng.drain(max_ticks=200)
        assert holder.status == "ok"

    def test_drain_max_ticks_truncation(self, model, params):
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params, _serve_config())
        eng.submit(_prompt(1, 7), 20)
        with pytest.raises(RuntimeError, match="drain exceeded 2 ticks"):
            eng.drain(max_ticks=2)


class TestDecodeHealthGuard:
    """Non-finite decode logits: quarantine the slot, keep the batch;
    watchdog warm restart on persistence."""

    def test_quarantine_storm_exact_pool_accounting(self, model,
                                                    params):
        """Poison EVERY active slot in one tick: all quarantined as
        `failed`, every freed block returns to the free list exactly
        once (no loss, no double-free), and the engine keeps serving —
        a fresh request admits onto the reclaimed blocks and is
        token-exact."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params,
                            _serve_config(guard_k_restart=3))
        storm = [eng.submit(_prompt(s, 7), 10) for s in (1, 2, 3)]
        eng.tick()
        assert eng.n_active == 3
        for i in eng.active_slots():
            eng.poison_slot(i)
        eng.tick()
        _assert_accounting(eng)
        assert [r.status for r in storm] == ["failed"] * 3
        assert all(r.finish_reason == "nonfinite_logits" for r in storm)
        free = eng.pool._free
        assert len(free) == len(set(free)) == eng.pool.num_usable, (
            "quarantine leaked or double-freed pool blocks"
        )
        fresh = eng.submit(_prompt(4, 7), 10)
        eng.drain(max_ticks=100)
        assert fresh.status == "ok"
        np.testing.assert_array_equal(
            np.asarray(fresh.tokens),
            _ref_tokens(model, params, fresh.prompt, 10),
            err_msg="post-storm admission corrupted",
        )
        assert eng.restarts == 0  # one poisoned tick < k_restart

    # demoted to slow (ISSUE-12 tier-1 budget): neighbor survival under
    # quarantine stays pinned by the slow chaos soak (every unpoisoned
    # request token-exact under a multi-fault schedule); the quick
    # quarantine-storm test keeps the freed-exactly-once accounting
    @pytest.mark.slow
    def test_neighbor_survives_quarantine_token_exact(self, model,
                                                      params):
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params, _serve_config(max_active=2))
        victim = eng.submit(_prompt(1, 7), 10)
        neighbor = eng.submit(_prompt(2, 13), 10)
        eng.tick()
        eng.poison_slot(eng.active_slots()[0])  # victim admitted first
        eng.drain(max_ticks=100)
        assert victim.status == "failed"
        assert neighbor.status == "ok"
        np.testing.assert_array_equal(
            np.asarray(neighbor.tokens),
            _ref_tokens(model, params, neighbor.prompt, 10),
            err_msg="neighbor diverged across a quarantine",
        )

    # demoted to slow (ISSUE-12 tier-1 budget): the watchdog-restart
    # resume path stays quick via test_tick_exception_warm_restart
    # (same restart machinery, one compile cheaper) and the consecutive-
    # poison trip predicate is unit-level in DecodeHealthGuard
    @pytest.mark.slow
    def test_watchdog_restart_after_consecutive_poison(self, model,
                                                       params):
        """k_restart consecutive poisoned ticks trip ONE warm restart;
        the in-flight survivors re-queue and finish token-exact on the
        rebuilt pool (same compiled programs)."""
        from tiny_deepspeed_tpu.resilience import (
            Chaos, ChaosServingEngine,
        )
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params,
                            _serve_config(max_active=2,
                                          guard_k_restart=2))
        ce = ChaosServingEngine(eng, Chaos(seed=3,
                                           tick_nan_steps=(1, 2)))
        reqs = [ce.submit(_prompt(s, 7), 12) for s in (1, 2, 3)]
        ce.drain(max_ticks=300)
        assert eng.restarts == 1
        statuses = sorted(r.status for r in reqs)
        assert statuses.count("failed") == 2  # one per poisoned tick
        survivors = [r for r in reqs if r.status == "ok"]
        assert survivors, "someone must survive the restart"
        for r in survivors:
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, 12),
                err_msg=f"request {r.id} diverged across warm restart",
            )
        _assert_accounting(eng)
        assert eng.pool.blocks_in_use == 0

    def test_tick_exception_warm_restart(self, model, params):
        """A chaos-injected prefill failure trips the watchdog: the
        half-admitted request re-queues and completes token-exact after
        the restart."""
        from tiny_deepspeed_tpu.resilience import (
            Chaos, ChaosServingEngine,
        )
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params, _serve_config())
        ce = ChaosServingEngine(eng,
                                Chaos(seed=4, prefill_raise_steps=(0,)))
        r = ce.submit(_prompt(5, 7), 8)
        ce.drain(max_ticks=100)
        assert eng.restarts == 1 and r.status == "ok"
        np.testing.assert_array_equal(
            np.asarray(r.tokens),
            _ref_tokens(model, params, r.prompt, 8),
            err_msg="request diverged across a prefill-failure restart",
        )

    def test_guard_off_propagates_tick_exceptions(self, model, params):
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(model, params,
                            _serve_config(health_guard=False))
        eng.submit(_prompt(1, 7), 4)
        eng.arm_prefill_exception(RuntimeError("boom"))
        with pytest.raises(RuntimeError, match="boom"):
            eng.tick()


class TestRequestJournal:
    """Crash-recoverable request journal + ServingEngine.recover."""

    def test_replay_tolerates_torn_tail_only(self, tmp_path):
        from tiny_deepspeed_tpu.serving.journal import RequestJournal
        p = str(tmp_path / "j.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"ev": "submit", "id": 0,
                                "prompt": [1, 2], "max_new": 4,
                                "deadline_s": None, "seed": 0}) + "\n")
            f.write(json.dumps({"ev": "tok", "id": 0,
                                "toks": [5]}) + "\n")
            f.write('{"ev": "tok", "id": 0, "to')  # torn by the crash
        pending, done = RequestJournal.replay(p)
        assert done == [] and len(pending) == 1
        assert pending[0]["tokens"] == [5]
        # the SAME torn line mid-file is corruption, not a crash mark
        with open(p, "a") as f:
            f.write("\n" + json.dumps({"ev": "end", "id": 0,
                                       "status": "ok",
                                       "finish": "length"}) + "\n")
        with pytest.raises(ValueError, match="corrupt journal"):
            RequestJournal.replay(p)

    # demoted to slow (ISSUE-12 tier-1 budget): same-engine recover
    # parity is subsumed quick by test_chaos_journal_kill_then_recover
    # (recover after a REAL lost tick) and by the fleet failover pin
    # (tests/test_fleet.py: journal replay onto a sibling, active AND
    # queued requests, token-identical)
    @pytest.mark.slow
    def test_recover_continues_token_exact(self, model, params,
                                           tmp_path):
        """Abandon an engine mid-flight (requests active AND queued);
        a fresh engine recovers from its journal and every interrupted
        request finishes with exactly the sequence an uninterrupted run
        produces."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        jp = str(tmp_path / "journal.jsonl")
        cfg = _serve_config(max_active=2)
        engA = ServingEngine(model, params, cfg, journal=jp)
        specs = [(6, 7, 10), (7, 13, 10), (8, 7, 10)]
        ra = [engA.submit(_prompt(s, n), new) for s, n, new in specs]
        for _ in range(4):
            engA.tick()
        assert any(r.tokens for r in ra) and not all(r.done for r in ra)
        engB = ServingEngine(model, params, cfg, journal=jp)
        rec = engB.recover()
        assert [r.id for r in rec] == [r.id for r in ra]
        engB.drain(max_ticks=200)
        for r, (s, n, new) in zip(rec, specs):
            assert r.status == "ok"
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, new),
                err_msg=f"recovered request {r.id} diverged",
            )

    def test_recover_closes_eos_finished_request(self, model, params,
                                                 tmp_path):
        """A request whose journaled prefix already ends in eos — but
        whose end line was torn away by the crash — must be CLOSED OUT
        at recovery, not re-queued: re-admitting it would decode past
        its eos and diverge from the uninterrupted run."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.serving.journal import RequestJournal
        jp = str(tmp_path / "journal.jsonl")
        eos = 42
        with open(jp, "w") as f:
            f.write(json.dumps({"ev": "submit", "id": 0,
                                "prompt": [1, 2, 3], "max_new": 8,
                                "deadline_s": None, "seed": 0}) + "\n")
            f.write(json.dumps({"ev": "tok", "id": 0,
                                "toks": [5, 9, eos]}) + "\n")
        eng = ServingEngine(model, params,
                            _serve_config(eos_id=eos), journal=jp)
        rec = eng.recover()
        assert rec == [] and eng.queue_depth == 0
        # the close-out landed an end line: a second replay sees the
        # request finished, so a crash loop cannot resurrect it either
        pending, done = RequestJournal.replay(jp)
        assert pending == [] and done == [0]

    def test_chaos_journal_kill_then_recover(self, model, params,
                                             tmp_path):
        """The chaos kill between journal-append and commit loses that
        tick's token lines; recovery re-decodes them to the same values
        (greedy continuation is position-keyed, not journal-keyed)."""
        from tiny_deepspeed_tpu.resilience import (
            Chaos, ChaosServingEngine,
        )
        from tiny_deepspeed_tpu.serving import ServingEngine, ServingKilled
        jp = str(tmp_path / "journal.jsonl")
        cfg = _serve_config(max_active=2)
        eng = ServingEngine(model, params, cfg, journal=jp)
        ce = ChaosServingEngine(eng, Chaos(seed=5, journal_kill_step=3))
        reqs = [ce.submit(_prompt(s, 7), 10) for s in (1, 2)]
        with pytest.raises(ServingKilled):
            ce.drain(max_ticks=100)
        assert not any(r.done for r in reqs)
        engB = ServingEngine(model, params, cfg, journal=jp)
        rec = engB.recover()
        assert len(rec) == 2
        # the killed tick's tokens are NOT in the journal prefix
        assert all(len(r.tokens) < len(o.tokens)
                   for r, o in zip(rec, reqs))
        engB.drain(max_ticks=200)
        for r in rec:
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, 10),
                err_msg=f"post-kill recovery diverged for {r.id}",
            )


class TestTemperatureDeterminism:
    # demoted to slow (ISSUE-12 tier-1 budget): the (seed, position)
    # key identity is unit-pinned quick in TestSamplingCore, and the
    # engine-level temp>0 tight-vs-roomy resume determinism stays
    # pinned by the slow spec-decoding determinism tests (both
    # drafters) plus this test in the slow tier
    @pytest.mark.slow
    def test_preemption_resume_deterministic_nongreedy(self, model,
                                                       params):
        """temperature > 0: a preempted-and-resumed request re-samples
        the SAME tokens as an undisturbed run — the sampling key for
        output position i of request r depends only on (r.seed, i),
        never on scheduler state (the ServingEngine docstring's
        guarantee)."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        kw = dict(block_tokens=8, temperature=1.0, top_k=16)
        tight = ServingEngine(
            model, params,
            _serve_config(max_active=3, num_blocks=5, **kw))
        roomy = ServingEngine(
            model, params,
            _serve_config(max_active=3, num_blocks=24, **kw))
        outs = []
        preemptions = []
        for eng in (tight, roomy):
            reqs = [eng.submit(_prompt(s, 10), 14, seed=100 + s)
                    for s in (1, 2, 3)]
            eng.drain(max_ticks=2000)
            outs.append([list(r.tokens) for r in reqs])
            preemptions.append(sum(r.preemptions for r in reqs))
        assert preemptions[0] >= 1, (
            "tight pool was sized to force at least one preemption"
        )
        assert preemptions[1] == 0
        assert outs[0] == outs[1], (
            "temperature>0 resume diverged from the undisturbed run"
        )


class TestRunTraceGuards:
    def test_no_progress_bound_names_state(self, model, params):
        """An engine that can never admit its queue must raise the
        no-progress bound (naming queue/pool state), not spin to
        max_ticks."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.serving.driver import Arrival, run_trace
        eng = ServingEngine(model, params, _serve_config())
        # simulate the post-incident pool shrink: every block vanishes
        # after the admission check, so the queued prompt never admits
        eng.pool._free = []
        with pytest.raises(RuntimeError,
                           match=r"no progress .* queue_depth=1"):
            run_trace(eng, [Arrival(0.0, _prompt(1, 7), 4)],
                      realtime=False, no_progress_ticks=10)


class TestOffPathSafety:
    def test_training_hlo_identical_with_serving_imported(self):
        """The training step's HLO is byte-identical with the serving
        package imported AND a live ServingEngine constructed — in a
        fresh subprocess, so the import order is genuinely
        before/after (an in-process pin would be vacuous once any other
        test imported serving).  The robustness layer rides the same
        pin: serving.guard and serving.journal are imported explicitly
        and the engine is built with the health guard ON (its default),
        so the ISSUE-8 acceptance 'training HLO byte-identical with
        serving.guard imported' is exactly what this asserts."""
        script = r"""
import json
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import sys
assert not any("serving" in m for m in sys.modules), "import leaked"
from tiny_deepspeed_tpu import GPTConfig, GPT2Model, SGD, SingleDevice
cfg = GPTConfig(block_size=32, vocab_size=128, n_layer=2, n_head=2,
                n_embd=32, compute_dtype=jnp.float32)
batch = (np.zeros((2, 32), np.int32), np.zeros((2, 32), np.int32))
eng = SingleDevice(GPT2Model(cfg), SGD(lr=0.1))
state = eng.init(jax.random.PRNGKey(0))
before = eng._step.lower(state, batch).as_text()
from tiny_deepspeed_tpu.serving import ServeConfig, ServingEngine
from tiny_deepspeed_tpu.serving import guard as _guard   # noqa: F401
from tiny_deepspeed_tpu.serving import journal as _jrn   # noqa: F401
from tiny_deepspeed_tpu.serving import spec as _spec     # noqa: F401
from tiny_deepspeed_tpu.serving import drafter as _drf   # noqa: F401
model = GPT2Model(cfg)
se = ServingEngine(model, model.init(jax.random.PRNGKey(0)),
                   ServeConfig(max_active=2, num_blocks=4,
                               block_tokens=8, health_guard=True))
# a SPECULATIVE engine constructed too: the spec machinery (drafter +
# verify program) must not perturb the training step's HLO either
se2 = ServingEngine(model, model.init(jax.random.PRNGKey(0)),
                    ServeConfig(max_active=2, num_blocks=4,
                                block_tokens=8, spec_draft="ngram",
                                spec_k=2))
# ...and the live observability plane ON (schema v15): telemetry +
# aggregator + SLO tracker attached, the /metrics exporter serving on a
# loopback port, a request actually served and scraped through it — all
# host-side by contract, so the training HLO must still not move
from tiny_deepspeed_tpu.telemetry import Telemetry
from tiny_deepspeed_tpu.telemetry.live import LiveAggregator, LiveExporter
from tiny_deepspeed_tpu.telemetry.slo import SLOTracker
import urllib.request
se.telemetry = Telemetry()
agg = LiveAggregator()
exp = LiveExporter(agg, slo=SLOTracker(), port=0)
lport = exp.start()
se.attach_live(agg)
se.attach_slo(SLOTracker())
lr = se.submit([1, 2, 3], 2)
se.drain(max_ticks=50)
assert lr.status == "ok", lr.status
scrape = urllib.request.urlopen(
    f"http://127.0.0.1:{lport}/metrics", timeout=10).read().decode()
assert "serve_tokens_total" in scrape, scrape[:200]
exp.stop()
eng2 = SingleDevice(GPT2Model(cfg), SGD(lr=0.1))
state2 = eng2.init(jax.random.PRNGKey(0))
after = eng2._step.lower(state2, batch).as_text()
print(json.dumps({"identical": before == after,
                  "n": len(before)}))
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # single-device is enough, and faster
        out = subprocess.run(
            [sys.executable, "-c", script],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))),
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        assert rec["identical"], (
            "training HLO changed with serving imported+instantiated"
        )


@pytest.mark.slow
class TestServingSoak:
    """Multi-minute acceptance runs: throughput vs serial, preemption."""

    def test_concurrent_beats_serial_at_greedy_parity(self):
        """>= 4 concurrent requests through the batched engine move more
        aggregate tokens/s than the same trace served one-at-a-time via
        `generate` — at token-exact greedy parity per request (the
        ISSUE's headline acceptance).

        Scale matters on the CPU mesh: below ~6 layers x 256 embd the
        per-TICK costs that batching amortizes (host round-trip, block-
        table gathers) exceed the per-token model compute itself and the
        fully-on-device serial fori_loop wins — measured 0.92x at
        2Lx32D, 0.71x at 4Lx128D, 12.7x at 6Lx256D (PROFILE.md "Decode
        under load").  The production claim is the 6x256 point; real
        serving models are orders of magnitude past the crossover."""
        import dataclasses

        from tiny_deepspeed_tpu.models import ALL_PRESETS, build_model
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.serving.driver import (
            poisson_trace, run_serial, run_trace,
        )
        cfg_m = dataclasses.replace(
            ALL_PRESETS["tiny"], n_layer=6, n_embd=256, n_head=4)
        model = build_model(cfg_m)
        params = model.init(jax.random.PRNGKey(0))
        trace = poisson_trace(12, rate_rps=None, prompt_lens=[7, 13],
                              max_new_tokens=24, vocab_size=512, seed=0)
        # max_seq_tokens sized to the trace (13 + 24 -> 40): the decode
        # panel reads 40 positions/slot, comparable to generate's cache
        cfg = _serve_config(max_active=4, num_blocks=32,
                            max_seq_tokens=40)
        eng = ServingEngine(model, params, cfg)
        # warm both paths on the SAME engine/jits: compiles out of the
        # measured wall
        run_trace(eng, trace[:4], realtime=False)
        run_serial(model, params, trace[:2])
        res = run_trace(eng, trace, realtime=False)
        ser = run_serial(model, params, trace)
        for rid, toks in enumerate(sorted(res["outputs"])):
            np.testing.assert_array_equal(
                np.asarray(res["outputs"][toks]),
                np.asarray(ser["outputs"][rid]),
                err_msg=f"trace request {rid} diverged from generate()",
            )
        assert res["mean_occupancy"] > 0.5  # truly concurrent
        assert res["tokens_per_s"] > 1.1 * ser["tokens_per_s"], (
            f"continuous batching {res['tokens_per_s']} tok/s did not "
            f"beat serial {ser['tokens_per_s']} tok/s"
        )

    def test_preemption_continues_greedy_exact(self, model, params):
        """Block exhaustion preempts the youngest request; after
        re-admission (re-prefilling prompt + produced tokens) its final
        output is still token-exact with `generate`."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        eng = ServingEngine(
            model, params,
            _serve_config(max_active=3, num_blocks=5, block_tokens=8))
        reqs = [eng.submit(_prompt(s, 10), 14) for s in (1, 2, 3)]
        eng.drain(max_ticks=2000)
        assert sum(r.preemptions for r in reqs) >= 1, (
            "pool was sized to force at least one preemption"
        )
        for r in reqs:
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, 14),
                err_msg=f"request {r.id} diverged after preemption",
            )


@pytest.mark.slow
class TestServingFaultSoak:
    """ISSUE-8 acceptance runs: real SIGKILL recovery, goodput under a
    sustained fault schedule.  Slow tier from the start — each pays
    fresh compiles in subprocesses or long drains."""

    def test_kill_mid_trace_sigkill_recovery_token_exact(self,
                                                         tmp_path):
        """SIGKILL the serving process from the journal's commit hook
        (a REAL death between journal-append and fsync), recover a
        fresh engine in a new process, and pin that every interrupted
        request's FINAL sequence equals the uninterrupted run's — the
        headline crash-recovery acceptance."""
        here = os.path.dirname(os.path.abspath(__file__))
        jp = str(tmp_path / "journal.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)

        def run(mode, check=True):
            out = subprocess.run(
                [sys.executable, os.path.join(here, "serving_worker.py"),
                 mode, jp],
                capture_output=True, text=True, timeout=600, env=env,
            )
            if check:
                assert out.returncode == 0, out.stderr[-2000:]
                return json.loads(out.stdout.strip().splitlines()[-1])
            return out

        straight = run("straight")["outputs"]
        killed = run("serve", check=False)
        assert killed.returncode == -9, (
            f"worker was supposed to die by SIGKILL, got rc="
            f"{killed.returncode}: {killed.stderr[-1000:]}"
        )
        assert os.path.exists(jp), "journal must survive the kill"
        rec = run("recover")
        assert rec["recovered"], "the kill left no in-flight requests?"
        assert all(s == "ok" for s in rec["statuses"].values())
        for rid, toks in rec["outputs"].items():
            assert toks == straight[rid], (
                f"request {rid} diverged across SIGKILL+recover:\n"
                f"  recovered: {toks}\n  straight:  {straight[rid]}"
            )

    def test_chaos_goodput_counts_exact_and_neighbors_unharmed(
            self, model, params):
        """Slot-poison + tick-delay chaos over a 10-request closed-loop
        trace: the poisoned requests fail, EVERY other request finishes
        `ok` AND token-exact with `generate` (no whole-batch failure),
        and the JSONL/summary status counts are exact for the
        deterministic fault schedule."""
        from tiny_deepspeed_tpu.resilience import (
            Chaos, ChaosServingEngine,
        )
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.serving.driver import (
            poisson_trace, run_trace,
        )
        trace = poisson_trace(10, rate_rps=None, prompt_lens=[7, 13],
                              max_new_tokens=12, vocab_size=128, seed=0)
        eng = ServingEngine(model, params,
                            _serve_config(max_active=4, num_blocks=24))
        # two NON-consecutive poisons (no watchdog restart) + one delay
        chaos = Chaos(seed=7, tick_nan_steps=(4, 8),
                      tick_delay_steps=(6,), delay_s=0.05)
        res = run_trace(ChaosServingEngine(eng, chaos), trace,
                        realtime=False)
        counts = res["status_counts"]
        assert counts == {"ok": 8, "shed": 0, "expired": 0,
                          "failed": 2}, counts
        assert res["restarts"] == 0
        n_nan = sum(1 for f in chaos.injected
                    if f["fault"] == "tick_nan" and f.get("slot", -1)
                    >= 0)
        assert counts["failed"] == n_nan
        assert 0 < res["ok_tokens_per_s"] <= res["tokens_per_s"]
        ok = [r for r in res["requests"] if r.status == "ok"]
        for r in ok:
            np.testing.assert_array_equal(
                np.asarray(r.tokens),
                _ref_tokens(model, params, r.prompt, 12),
                err_msg=f"unpoisoned request {r.id} diverged under "
                        "chaos",
            )
