# Copyright 2026 tiny-deepspeed-tpu authors
# SPDX-License-Identifier: Apache-2.0

"""Live fleet observability plane (ISSUE 20): streaming metric
aggregation, the /metrics exporter, cross-engine request tracing, and
SLO error-budget accounting.

Acceptance pins:
  * a chaos fleet run (disagg handoff + engine_kill failover) yields a
    Chrome trace with ONE request's spans on TWO replica processes,
    correlated by the `trace_id` in their span args — and /metrics
    scraped MID-RUN parses with per-replica gauge labels;
  * the exporter is host-side only: aggregating and rendering a
    poisoned registry snapshot must never call `__array__` (the PR-10
    flight-pin style, applied to the scrape path);
  * Prometheus text round-trips through the minimal parser (types,
    labels, summary quantiles);
  * `slo` records validate under schema v15, burn alerts fire on the
    TRANSITION into burning, and a fast burn arms the flight ring;
  * flight flushes in a SHARED fleet stream anchor by their replica_id
    key — file order is only the fallback for records without one
    (the ONE documented rule, trace.py::serving_chrome_trace).
"""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tiny_deepspeed_tpu import GPTConfig, GPT2Model

CFG = dict(block_size=64, vocab_size=128, n_layer=2, n_head=2,
           n_embd=32, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return GPT2Model(GPTConfig(**CFG))


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0))


def _prompt(seed, n, vocab=128):
    return np.asarray(
        jax.random.randint(jax.random.PRNGKey(seed), (n,), 0, vocab),
        np.int32,
    ).tolist()


def _serve_config(**kw):
    from tiny_deepspeed_tpu.serving import ServeConfig
    kw.setdefault("max_active", 2)
    kw.setdefault("num_blocks", 16)
    kw.setdefault("block_tokens", 8)
    kw.setdefault("max_seq_tokens", 40)
    return ServeConfig(**kw)


def _get(url):
    return urllib.request.urlopen(url, timeout=10).read().decode()


# ---------------------------------------------------------------------------
# gauge labels (satellite a)
# ---------------------------------------------------------------------------

class TestGaugeLabels:
    def test_gauge_key_roundtrip(self):
        from tiny_deepspeed_tpu.telemetry.live import (
            gauge_key, parse_gauge_key,
        )
        k = gauge_key("serve_queue_depth", replica=0)
        assert k == "serve_queue_depth{replica=0}"
        assert parse_gauge_key(k) == ("serve_queue_depth",
                                      {"replica": "0"})
        # bare keys parse to themselves — pre-v15 files stay readable
        assert parse_gauge_key("serve_queue_depth") == (
            "serve_queue_depth", {})
        # labels sort, so the key is canonical regardless of kw order
        assert gauge_key("g", b="2", a="1") == gauge_key("g", a="1", b="2")

    def test_registry_labels_qualify_the_key(self):
        """Two replicas writing the same gauge through a SHARED registry
        land on distinct keys — the PR-16 last-writer-wins wart — while
        replica=None (single-engine) keeps the historical bare key."""
        from tiny_deepspeed_tpu.telemetry import Telemetry
        tel = Telemetry()
        tel.gauge("serve_queue_depth", 3.0, replica=0)
        tel.gauge("serve_queue_depth", 5.0, replica=1)
        tel.gauge("serve_queue_depth", 7.0)
        g = tel.gauges
        assert g["serve_queue_depth{replica=0}"] == 3.0
        assert g["serve_queue_depth{replica=1}"] == 5.0
        assert g["serve_queue_depth"] == 7.0
        # the labeled read returns the labeled value
        assert tel.gauge("serve_queue_depth", replica=1) == 5.0

    def test_fleet_run_emits_per_replica_gauges(self, model, params,
                                                tmp_path):
        """End-to-end: replica-id'd engines sharing one registry leave
        BOTH replicas' last-tick state in the summary gauges."""
        from tiny_deepspeed_tpu.fleet import FleetRouter
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.telemetry import Telemetry
        tel = Telemetry()
        engines = [
            ServingEngine(model, params, _serve_config(),
                          replica_id=i, telemetry=tel)
            for i in range(2)
        ]
        router = FleetRouter(engines, telemetry=tel)
        reqs = [router.submit(_prompt(s, 7), 6) for s in (1, 2, 3, 4)]
        router.drain(max_ticks=300)
        assert all(r.status == "ok" for r in reqs)
        g = tel.gauges
        for rid in (0, 1):
            assert f"serve_queue_depth{{replica={rid}}}" in g, sorted(g)
            assert g[f"serve_queue_depth{{replica={rid}}}"] == 0.0


# ---------------------------------------------------------------------------
# streaming aggregation + prometheus round-trip (tentpole 1, satellite c)
# ---------------------------------------------------------------------------

class TestLiveAggregator:
    def test_counter_deltas_rates_and_reset(self):
        from tiny_deepspeed_tpu.telemetry.live import LiveAggregator
        agg = LiveAggregator()
        for i, v in enumerate((10.0, 16.0, 25.0)):
            agg.ingest({"counters": {"serve_tokens": v}}, t=float(i))
        # rate = sum of deltas inside the window / elapsed in-window
        assert agg.rate("serve_tokens", window_s=30.0, t=2.0) > 0.0
        snap = agg.snapshot()
        assert snap["counters"]["serve_tokens"] == 25.0
        # a registry reset (fresh engine, counter back near zero) must
        # restart the series, not record a huge negative delta
        agg.ingest({"counters": {"serve_tokens": 2.0}}, t=3.0)
        assert agg.snapshot()["counters"]["serve_tokens"] == 2.0
        assert agg.rate("serve_tokens", window_s=30.0, t=3.0) > 0.0

    def test_window_quantiles_per_labeled_gauge(self):
        from tiny_deepspeed_tpu.telemetry.live import LiveAggregator
        agg = LiveAggregator()
        for i in range(10):
            agg.ingest(
                {"gauges": {"serve_queue_depth{replica=0}": float(i)}},
                replica=0, t=float(i))
        q = agg.window_quantiles("serve_queue_depth{replica=0}")
        assert q["p50"] == pytest.approx(4.5)
        assert q["p99"] >= q["p95"] >= q["p50"]
        assert agg.snapshot()["ticks"] == {"0": 10}

    def test_prometheus_text_roundtrip(self):
        """Render -> parse is lossless for the shapes we emit: counter
        totals, labeled gauges, summary quantiles + count/sum."""
        from tiny_deepspeed_tpu.telemetry import live
        agg = live.LiveAggregator()
        agg.ingest({
            "counters": {"serve_tokens": 42.0},
            "gauges": {"serve_queue_depth{replica=0}": 3.0,
                       "serve_queue_depth{replica=1}": 5.0,
                       "serve_eviction_rate": 0.25},
            "histograms": {"serve_token_latency": {
                "count": 8, "mean": 0.5, "p50": 0.4, "p95": 0.9,
                "p99": 1.0, "max": 1.2}},
        }, replica=0, t=1.0)
        text = agg.prometheus_text(t=1.0)
        doc = live.parse_prometheus_text(text)
        assert doc["types"]["serve_tokens_total"] == "counter"
        assert doc["types"]["serve_queue_depth"] == "gauge"
        assert doc["types"]["serve_token_latency"] == "summary"
        samples = {(n, tuple(sorted(lb.items()))): v
                   for n, lb, v in doc["samples"]}
        assert samples[("serve_tokens_total", ())] == 42.0
        assert samples[("serve_queue_depth",
                        (("replica", "0"),))] == 3.0
        assert samples[("serve_queue_depth",
                        (("replica", "1"),))] == 5.0
        assert samples[("serve_token_latency",
                        (("quantile", "0.95"),))] == 0.9
        assert samples[("serve_token_latency_count", ())] == 8.0
        assert samples[("serve_token_latency_sum", ())] == \
            pytest.approx(4.0)
        assert samples[("live_ticks_total", (("replica", "0"),))] == 1.0

    def test_parser_rejects_garbage(self):
        from tiny_deepspeed_tpu.telemetry.live import (
            parse_prometheus_text,
        )
        with pytest.raises(ValueError):
            parse_prometheus_text("not a metric line at all!!!\n")

    def test_aggregation_never_syncs_devices(self):
        """The scrape path is host-side by CONSTRUCTION: a value that
        would detonate on `__array__` (a stand-in for a device array)
        must pass through ingest -> prometheus_text -> healthz via
        plain float() only (the PR-10 flight-recorder pin, applied to
        the exporter's hot path)."""
        from tiny_deepspeed_tpu.telemetry.live import LiveAggregator

        class _Unsyncable:
            def __array__(self, *a, **k):
                raise AssertionError(
                    "live plane materialized a device array")

            def __float__(self):
                return 2.5

        agg = LiveAggregator()
        agg.ingest({
            "counters": {"serve_tokens": _Unsyncable()},
            "gauges": {"serve_queue_depth{replica=0}": _Unsyncable()},
            "histograms": {"h": {"count": 1, "mean": _Unsyncable()}},
        }, replica=0, t=1.0)
        text = agg.prometheus_text(t=1.0)
        assert "serve_tokens_total 2.5" in text
        hz = agg.healthz(t=2.0)
        assert hz["replicas"]["0"]["serve_queue_depth"] == 2.5


class TestExporter:
    def test_http_endpoints(self):
        from tiny_deepspeed_tpu.telemetry import live, slo
        agg = live.LiveAggregator()
        agg.ingest({"counters": {"serve_tokens": 5.0},
                    "gauges": {"serve_queue_depth{replica=0}": 1.0}},
                   replica=0, t=1.0)
        tracker = slo.SLOTracker()
        tracker.observe(tenant=None, ok=True, latency_s=0.1, t=1.0)
        with live.LiveExporter(agg, slo=tracker, port=0) as exp:
            base = f"http://127.0.0.1:{exp.port}"
            metrics = _get(base + "/metrics")
            assert live.parse_prometheus_text(metrics)["samples"]
            hz = json.loads(_get(base + "/healthz"))
            assert hz["ok"] is True and "0" in hz["replicas"]
            sl = json.loads(_get(base + "/slo"))
            assert sl["attainment"] == 1.0
            with pytest.raises(urllib.error.HTTPError):
                _get(base + "/nope")
        assert agg.scrapes >= 1


# ---------------------------------------------------------------------------
# SLO error budgets (tentpole 4)
# ---------------------------------------------------------------------------

class TestSLO:
    def test_objective_grammar_and_goodness(self):
        from tiny_deepspeed_tpu.telemetry.slo import SLOObjective
        obj = SLOObjective.parse("target=0.95,ttft=0.5,latency=5")
        assert (obj.target, obj.ttft_s, obj.latency_s) == (0.95, 0.5, 5.0)
        assert obj.good(ok=True, ttft_s=0.4, latency_s=4.0)
        assert not obj.good(ok=True, ttft_s=0.6, latency_s=4.0)
        assert not obj.good(ok=True, ttft_s=0.4, latency_s=6.0)
        assert not obj.good(ok=False, ttft_s=0.1, latency_s=0.2)
        # an unset bound doesn't constrain; a missing measurement fails
        # a set bound (can't prove it was met)
        loose = SLOObjective.parse("target=0.9")
        assert loose.good(ok=True, ttft_s=None, latency_s=None)
        assert not obj.good(ok=True, ttft_s=None, latency_s=1.0)
        with pytest.raises(ValueError, match="unknown SLO key"):
            SLOObjective.parse("target=0.9,bogus=1")
        with pytest.raises(ValueError, match="target"):
            SLOObjective(target=1.0)

    def test_burn_alert_fires_on_transition_only(self):
        """burn = bad_frac / budget.  target=0.9 -> budget 0.1, so one
        bad in two requests is burn 5.0; the fast rule (threshold 14)
        needs > 1.4 bad fraction... use a tighter threshold to pin the
        TRANSITION semantics: fire once entering, re-arm after clearing."""
        from tiny_deepspeed_tpu.telemetry.slo import (
            SLOObjective, SLOTracker,
        )
        fired = []
        tr = SLOTracker(default=SLOObjective(target=0.9),
                        windows_s=(10.0, 100.0), fast_burn=4.0,
                        slow_burn=100.0, on_alert=fired.append)
        tr.observe(tenant=None, ok=False, latency_s=1.0, t=1.0)
        tr.observe(tenant=None, ok=False, latency_s=1.0, t=2.0)
        # bad frac 1.0 / budget 0.1 = burn 10 >= 4: fires, once
        alerts = tr.check(t=2.0)
        assert len(alerts) == 1 and alerts[0]["kind"] == "fast_burn"
        assert alerts[0]["burn"] == pytest.approx(10.0)
        assert tr.check(t=2.5) == []  # still burning: no re-fire
        assert fired == alerts
        # window slides past the failures -> below threshold -> re-arm
        assert tr.check(t=50.0) == []
        tr.observe(tenant=None, ok=False, latency_s=1.0, t=51.0)
        assert len(tr.check(t=51.0)) == 1  # fires again after clearing

    def test_attainment_and_advise(self):
        from tiny_deepspeed_tpu.telemetry.slo import (
            SLOObjective, SLOTracker,
        )
        tr = SLOTracker(default=SLOObjective(target=0.5))
        for i, ok in enumerate((True, True, True, False)):
            tr.observe(tenant="t1", ok=ok, latency_s=0.1,
                       replica=i % 2, t=float(i))
        assert tr.attainment("t1") == 0.75
        assert tr.attainment() == 0.75
        # the failure landed on replica 1 (i=3): advise penalizes it
        assert tr.advise(1, t=4.0) > tr.advise(0, t=4.0)
        assert tr.advise(7, t=4.0) == 0.0  # no traffic advises nothing
        snap = tr.snapshot(t=4.0)
        assert snap["tenants"]["t1"]["attainment"] == 0.75
        assert snap["tenants"]["t1"]["budget_spent_frac"] == \
            pytest.approx(0.5)

    def test_slo_record_validates_under_schema_v15(self):
        from tiny_deepspeed_tpu.telemetry import schema
        from tiny_deepspeed_tpu.telemetry.slo import SLOTracker
        assert schema.SCHEMA_VERSION >= 15
        assert "slo" in schema.META_KINDS
        recs = []

        class _Log:
            def log_meta(self, **kw):
                recs.append(kw)

        tr = SLOTracker()
        tr.observe(tenant="a", ok=True, latency_s=0.1, t=1.0)
        tr.record(_Log(), step=7)
        assert recs and recs[0]["kind"] == "slo"
        assert recs[0]["at_step"] == 7
        rec = dict(recs[0], ts=0.0)
        assert not schema.validate_record(rec), \
            schema.validate_record(rec)

    def test_fast_burn_arms_flight_and_persists_record(self, model,
                                                       params, tmp_path):
        """Engine integration: a run whose every request blows its
        latency objective trips fast burn at the first terminal —
        the flight ring flushes with reason slo_fast_burn and an `slo`
        record lands in the sidecar."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.telemetry import Telemetry
        from tiny_deepspeed_tpu.telemetry.schema import SCHEMA_VERSION
        from tiny_deepspeed_tpu.telemetry.slo import (
            SLOObjective, SLOTracker,
        )
        from tiny_deepspeed_tpu.utils.profiling import MetricsLogger
        path = str(tmp_path / "burn.jsonl")
        with MetricsLogger(path, stdout=False) as ml:
            ml.log_meta(schema_version=SCHEMA_VERSION, engine="serve:t")
            eng = ServingEngine(model, params, _serve_config(),
                                telemetry=Telemetry(), logger=ml)
            # an objective nothing can meet: every terminal is bad, so
            # burn = 1/budget = 20 — over the default fast threshold
            eng.attach_slo(SLOTracker(
                default=SLOObjective(target=0.95, latency_s=1e-9)))
            r = eng.submit(_prompt(1, 7), 6)
            eng.drain(max_ticks=100)
        assert r.status == "ok"  # served fine — the SLO is what failed
        metas = [json.loads(ln) for ln in open(path)]
        slos = [m for m in metas if m.get("kind") == "slo"]
        assert slos, "no slo record persisted on the alert"
        assert slos[-1]["attainment"] == 0.0
        assert any(a["kind"] == "fast_burn"
                   for a in slos[-1]["alerts"])
        flights = [m for m in metas if m.get("kind") == "flight"]
        assert any(m.get("reason") == "slo_fast_burn" for m in flights), \
            [m.get("reason") for m in flights]
        from tiny_deepspeed_tpu.telemetry import schema
        counts, errs = schema.validate_file(path)
        assert not errs, errs[:5]


# ---------------------------------------------------------------------------
# cross-engine tracing (tentpole 3, satellite f)
# ---------------------------------------------------------------------------

class TestEventAttribution:
    def test_marker_rule_unit(self):
        """The ONE rule, on synthetic events: leave-markers attribute
        backward to their replica, arrive-markers forward, the trailing
        segment to the record's replica."""
        from tiny_deepspeed_tpu.telemetry.trace import _event_replicas
        events = [
            ["submitted", 0.0],            # -> 0 (exported flushes back)
            ["admitted", 0.1, 0],          # -> 0
            ["exported", 0.2, 0, 0],       # leave: 0
            ["imported", 0.3, 1, 1],       # arrive: 1, assigns forward
            ["terminal:ok", 0.4, 1],       # -> 1
        ]
        assert _event_replicas(events, 1) == [0, 0, 0, 1, 1]
        # no markers at all: everything belongs to the record's replica
        assert _event_replicas([["submitted", 0.0], ["admitted", 0.1, 0]],
                               None) == [None, None]
        # engine_lost (leave) then recovered (arrive) — the failover
        # shape: pre-death events on the dead replica, post on the
        # sibling
        events = [
            ["submitted", 0.0],
            ["engine_lost", 0.2, None, 0],
            ["recovered", 0.3, None, 1],
            ["admitted", 0.4, 0],
            ["terminal:ok", 0.5, 0],
        ]
        assert _event_replicas(events, 1) == [0, 0, 1, 1, 1]

    def test_trace_id_survives_journal_recovery(self, model, params,
                                                tmp_path):
        """trace_id is derived from the request id, so a journal replay
        onto a sibling reconstructs the SAME id — correlation survives
        the crash it exists to explain."""
        from tiny_deepspeed_tpu.serving import ServingEngine
        jp = str(tmp_path / "trace.jsonl")
        a = ServingEngine(model, params, _serve_config(), journal=jp)
        orig = a.submit(_prompt(1, 7), 6)
        assert orig.trace_id == f"t{orig.id:06d}"
        b = ServingEngine(model, params, _serve_config())
        rec = b.recover(journal=jp)
        assert len(rec) == 1
        assert rec[0].trace_id == orig.trace_id
        b.drain(max_ticks=100)
        assert rec[0].status == "ok"

    def test_disagg_trace_spans_two_replica_processes(self, model,
                                                      params, tmp_path):
        """Half of THE acceptance: a disagg run's request has windows on
        the prefill replica's process AND the decode replica's process,
        correlated by args.trace_id, with the migration wait labeled."""
        from tiny_deepspeed_tpu.fleet import DisaggEngine
        from tiny_deepspeed_tpu.telemetry import trace
        from tiny_deepspeed_tpu.utils.profiling import MetricsLogger
        jsonl = str(tmp_path / "disagg.jsonl")
        with MetricsLogger(jsonl, stdout=False) as logger:
            dis = DisaggEngine(model, params, _serve_config(),
                               logger=logger)
            reqs = [dis.submit(_prompt(s, 7), 8) for s in (1, 2)]
            dis.drain(max_ticks=300)
        assert all(r.status == "ok" for r in reqs)
        metas, _, errs = trace.load_run(jsonl)
        assert not errs
        doc = trace.serving_chrome_trace(metas, source=jsonl)
        assert doc["otherData"]["replicas"] == [0, 1]
        tid = reqs[0].trace_id
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"
                 and e.get("args", {}).get("trace_id") == tid]
        pids = {e["pid"] for e in spans}
        assert pids == {2, 3}, (pids, spans)  # replica 0 AND replica 1
        assert any(e["args"].get("window") == "migration wait"
                   for e in spans), [e["args"] for e in spans]
        # comp_migrate_s rides the record and partitions with the rest
        rec = next(m for m in metas if m.get("kind") == "request"
                   and m.get("trace_id") == tid)
        assert rec.get("comp_migrate_s", 0.0) > 0.0
        comp = sum(rec[k] for k in rec if k.startswith("comp_"))
        assert comp == pytest.approx(rec["lat_s"], abs=2e-5)

    def test_failover_trace_and_midrun_scrape(self, model, params,
                                              tmp_path):
        """THE acceptance, failover half: chaos engine_kill mid-trace,
        the dead replica's requests finish on the sibling; the Chrome
        trace shows one request's spans on both replica processes under
        one trace_id, and /metrics scraped MID-RUN parses with
        per-replica labels."""
        from tiny_deepspeed_tpu.fleet import FleetRouter
        from tiny_deepspeed_tpu.resilience import Chaos, ChaosServingEngine
        from tiny_deepspeed_tpu.serving import ServingEngine
        from tiny_deepspeed_tpu.telemetry import Telemetry, live, trace
        from tiny_deepspeed_tpu.telemetry.slo import SLOTracker
        from tiny_deepspeed_tpu.utils.profiling import MetricsLogger
        jsonl = str(tmp_path / "fleet.jsonl")
        tel = Telemetry()
        agg = live.LiveAggregator()
        tracker = SLOTracker()
        with MetricsLogger(jsonl, stdout=False) as logger:
            engines = []
            for i in range(2):
                e = ServingEngine(
                    model, params, _serve_config(),
                    journal=str(tmp_path / f"j.r{i}.jsonl"),
                    replica_id=i, telemetry=tel, logger=logger)
                if i == 0:
                    e = ChaosServingEngine(
                        e, Chaos(seed=3, engine_kill_step=3))
                engines.append(e)
            router = FleetRouter(engines, telemetry=tel, logger=logger)
            router.attach_live(agg)
            router.attach_slo(tracker)
            reqs = [router.submit(_prompt(s, 7), 10)
                    for s in (1, 2, 3, 4)]
            with live.LiveExporter(agg, slo=tracker, port=0) as exp:
                for _ in range(2):
                    router.tick()
                # the MID-RUN scrape: both replicas have ticked, the
                # run is live, requests in flight
                text = _get(f"http://127.0.0.1:{exp.port}/metrics")
                doc = live.parse_prometheus_text(text)
                qd = {lb.get("replica"): v for n, lb, v in doc["samples"]
                      if n == "serve_queue_depth"}
                assert "0" in qd and "1" in qd, doc["samples"][:10]
                ticks = {lb["replica"] for n, lb, v in doc["samples"]
                         if n == "live_ticks_total"}
                assert ticks == {"0", "1"}
                hz = json.loads(
                    _get(f"http://127.0.0.1:{exp.port}/healthz"))
                assert set(hz["replicas"]) == {"0", "1"}
                router.drain(max_ticks=500)
        assert router.failovers == 1
        assert all(r.status == "ok" for r in reqs)
        # a request that crossed the failover: its spans sit on BOTH
        # replica processes under one trace_id
        metas, _, errs = trace.load_run(jsonl)
        assert not errs
        doc = trace.serving_chrome_trace(metas, source=jsonl)
        assert doc["otherData"]["replicas"] == [0, 1]
        crossed = None
        for r in reqs:
            spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"
                     and e.get("args", {}).get("trace_id") == r.trace_id]
            if {e["pid"] for e in spans} == {2, 3}:
                crossed = (r, spans)
                break
        assert crossed is not None, (
            "no request's spans crossed both replica processes")
        # the exporter aggregated both replicas' tick streams
        assert set(agg.snapshot()["ticks"]) == {"0", "1"}

    def test_flight_anchors_by_replica_key_in_shared_stream(self):
        """Satellite f's two-replica fixture: both replicas' tick
        counters run 0..2 in ONE interleaved stream.  A flight flush
        carrying replica_id=1 must anchor on replica 1's tick — the
        explicit-key half of the rule — even though replica 0's
        same-numbered tick is nearer in file order; a flush WITHOUT
        the key falls back to file order (last before, else first
        after)."""
        from tiny_deepspeed_tpu.telemetry import trace

        def tick(rep, i, t):
            return {"kind": "tick", "ts": t, "tick": i, "t_s": t,
                    "wall_s": 0.01, "replica_id": rep}

        metas = [
            {"kind": "run_meta", "ts": 0.0, "serve": {"max_active": 1}},
            tick(0, 0, 1.0), tick(1, 0, 1.5),
            tick(0, 1, 2.0), tick(1, 1, 2.5),
            tick(0, 2, 3.0),
            {"kind": "flight", "ts": 3.1, "reason": "serve_restart",
             "at_step": 1, "steps": [], "replica_id": 1},
            {"kind": "flight", "ts": 3.2, "reason": "slo_fast_burn",
             "at_step": 2, "steps": []},
            tick(1, 2, 3.5),
        ]
        doc = trace.serving_chrome_trace(metas, source="fixture")
        marks = [e for e in doc["traceEvents"]
                 if e.get("name", "").startswith("flight flush")]
        by_reason = {e["name"]: e for e in marks}
        keyed = by_reason["flight flush (serve_restart)"]
        # replica key wins: pid 3 (replica 1), anchored at ITS tick 1
        # (t_s 2.5), not replica 0's nearer-in-file tick 1
        assert keyed["pid"] == 3
        assert keyed["ts"] == pytest.approx((2.5 - 1.0 + 0.01) * 1e6)
        # no key: file order — last tick==2 written before the flush is
        # replica 0's (t_s 3.0), so it lands on pid 2
        unkeyed = by_reason["flight flush (slo_fast_burn)"]
        assert unkeyed["pid"] == 2
        assert unkeyed["ts"] == pytest.approx((3.0 - 1.0 + 0.01) * 1e6)


# ---------------------------------------------------------------------------
# dashboards + CLI surfaces (satellites b, e)
# ---------------------------------------------------------------------------

class TestReportSurfaces:
    def _report(self, metas):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "serve_report_under_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts",
                "serve_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.render_serve_report(metas, source="t.jsonl")

    def test_slo_budget_section_and_replica_gauges(self):
        metas = [
            {"kind": "run_meta", "ts": 0.0, "serve": {"max_active": 1}},
            {"kind": "tick", "ts": 1.0, "tick": 0, "t_s": 1.0,
             "wall_s": 0.01, "replica_id": 0},
            {"kind": "request", "ts": 2.0, "request_id": 0,
             "prompt_tokens": 4, "new_tokens": 2, "preemptions": 0,
             "status": "ok", "finish": "length", "lat_s": 0.5,
             "replica_id": 0},
            {"kind": "slo", "ts": 3.0, "windows": {"s": [30.0, 300.0]},
             "attainment": 0.75,
             "tenants": {"t1": {
                 "objective": {"target": 0.9, "ttft_s": None,
                               "latency_s": 5.0},
                 "requests": 4, "good": 3, "attainment": 0.75,
                 "budget_spent_frac": 1.0,
                 "burn": {"30s": 10.0, "300s": 2.5}}},
             "alerts": [{"tenant": "t1", "kind": "fast_burn",
                         "burn": 10.0, "window_s": 30.0,
                         "threshold": 14.0, "t": 2.5}]},
            {"kind": "telemetry_summary", "ts": 4.0, "gauges": {
                "serve_queue_depth{replica=0}": 2.0,
                "serve_queue_depth{replica=1}": 0.0,
                "serve_restarts{replica=1}": 1.0}},
            {"kind": "flight", "ts": 5.0, "reason": "slo_fast_burn",
             "at_step": 0, "steps": []},
        ]
        rep = self._report(metas)
        assert "## SLO budgets" in rep
        assert "75.00%" in rep                 # attainment formatting
        assert "fast_burn" in rep and "t1" in rep
        assert "Per-replica gauges" in rep
        # both replicas' rows render from the labeled keys
        assert "| 0 | 2 |" in rep and "| 1 | 0 |" in rep, rep
        assert "slo_fast_burn" in rep          # flights filter widened

    def test_migrate_component_in_tail_table(self):
        metas = [
            {"kind": "run_meta", "ts": 0.0, "serve": {"max_active": 1}},
            {"kind": "tick", "ts": 1.0, "tick": 0, "t_s": 1.0,
             "wall_s": 0.01},
            {"kind": "request", "ts": 2.0, "request_id": 0,
             "prompt_tokens": 4, "new_tokens": 2, "preemptions": 0,
             "status": "ok", "finish": "length", "lat_s": 1.0,
             "comp_queue_s": 0.1, "comp_prefill_s": 0.1,
             "comp_decode_s": 0.1, "comp_preempt_s": 0.0,
             "comp_restart_s": 0.0, "comp_migrate_s": 0.7},
        ]
        rep = self._report(metas)
        assert "migration-wait" in rep
        assert "**migration-wait** dominates" in rep

    def test_serve_bench_live_smoke(self, tmp_path):
        """The CLI smoke (satellite b): --live-port 0 + --slo on a tiny
        closed-loop run — exporter line on stderr, slo block in the
        summary JSON, an `slo` record in the sidecar, and both
        report_run --check and serve_report accept the file."""
        import os
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        sidecar = str(tmp_path / "live.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        out = subprocess.run(
            [sys.executable,
             os.path.join(repo, "scripts", "serve_bench.py"),
             "--cpu", "--requests", "4", "--closed-loop",
             "--prompt-lens", "8,12", "--max-new-tokens", "6",
             "--live-port", "0", "--slo", "target=0.9,latency=60",
             "--jsonl", sidecar],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=repo)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "live exporter -> http://127.0.0.1:" in out.stderr
        assert "aggregated" in out.stderr  # scrape/tick stats line
        summary = json.loads(out.stdout.strip().splitlines()[-1])
        assert summary["slo"]["attainment"] == 1.0
        metas = [json.loads(ln) for ln in open(sidecar)]
        assert any(m.get("kind") == "slo" for m in metas)
        chk = subprocess.run(
            [sys.executable,
             os.path.join(repo, "scripts", "report_run.py"),
             "--check", sidecar],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=repo)
        assert chk.returncode == 0, chk.stdout + chk.stderr
        rep = subprocess.run(
            [sys.executable,
             os.path.join(repo, "scripts", "serve_report.py"), sidecar],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=repo)
        assert rep.returncode == 0, rep.stdout + rep.stderr
        assert "## SLO budgets" in rep.stdout
